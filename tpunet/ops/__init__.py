"""tpunet.ops — TPU kernels and memory-fused ops for the hot paths.

The reference (bagua-net) has no compute kernels — it is a transport. This
package holds the compute-side hot ops our framework's model layer needs so
the end-to-end benchmarks (VGG16-class DP, long-context transformer) keep the
MXU fed: a flash-attention kernel (Pallas) with an online-softmax inner loop,
used both for local attention and as the per-block compute of ring attention,
and a blockwise fused cross-entropy (pure XLA scan) that never materializes
the (tokens, vocab) logits.
"""

from tpunet.ops.flash_attention import attention_reference, flash_attention
from tpunet.ops.fused_xent import blockwise_cross_entropy

__all__ = ["flash_attention", "attention_reference", "blockwise_cross_entropy"]
