"""tpunet.ops — TPU kernels for the hot ops (Pallas).

The reference (bagua-net) has no compute kernels — it is a transport. This
package holds the compute-side hot ops our framework's model layer needs so
the end-to-end benchmarks (VGG16-class DP, long-context transformer) keep the
MXU fed: a flash-attention kernel with an online-softmax inner loop, used both
for local attention and as the per-block compute of ring attention.
"""

from tpunet.ops.flash_attention import attention_reference, flash_attention

__all__ = ["flash_attention", "attention_reference"]
