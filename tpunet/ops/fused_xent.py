"""Blockwise (memory-fused) softmax cross-entropy over a large vocabulary.

The lm-head + cross-entropy of a 32k-vocab model materializes a
(batch*seq, vocab) f32 logits tensor — 2 GB at the v5e headline shape and
the single largest HBM resident in training. This computes the exact same
loss with only ONE vocab block of logits live at a time: a `lax.scan` over
vocab blocks carrying an online logsumexp (the flash-attention trick, FLASH
over the vocab axis instead of sequence), with `jax.checkpoint` on the
block body so autodiff recomputes each block's logits in the backward pass
instead of stashing them (which would reconstruct the full tensor).

XLA-idiomatic by design: each block is one big MXU matmul
(N×d @ d×block_vocab, f32 accumulation), the scan is compiler-friendly
sequential control flow, and no Pallas/Mosaic surface is involved — the
memory win comes from the algorithm, not a kernel.

No reference counterpart (the reference has no model/loss code at all —
SURVEY §2.3); this is the long-context enabler for the tpunet model tier.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def blockwise_cross_entropy(feats, kernel, labels, block_vocab: int = 8192,
                            return_lse: bool = False):
    """Exact per-token negative log-likelihood without full logits.

    feats: (N, d) floating (bf16/f32) — final hidden states.
    kernel: (d, V) lm-head weights (cast to feats.dtype for the matmul;
        accumulation is f32 via preferred_element_type).
    labels: (N,) int32; negatives wrap python-style (-1 == V-1) and
        labels >= V produce NaN, matching optax exactly.
    Returns (N,) f32 losses: logsumexp(logits) - logits[label]; with
    return_lse=True, (losses, lse) — the online logsumexp is computed
    anyway, and exposing it gives z-loss regularization for free (the
    logits still never materialize).

    Matches optax.softmax_cross_entropy_with_integer_labels(feats @ kernel)
    to f32 rounding; peak memory is O(N * block_vocab) instead of O(N * V).
    """
    n_tokens, d = feats.shape
    vocab = kernel.shape[1]
    if labels.shape != (n_tokens,):
        raise ValueError(f"labels shape {labels.shape} != ({n_tokens},)")
    # Mirror optax's out-of-range semantics exactly: negative labels wrap
    # python-style (-1 == vocab-1); labels >= vocab yield NaN (loud, not a
    # silently-degraded plain logsumexp).
    labels = jnp.where(labels < 0, labels + vocab, labels)
    valid = (labels >= 0) & (labels < vocab)
    block_vocab = min(block_vocab, vocab)
    n_blocks = -(-vocab // block_vocab)
    padded = n_blocks * block_vocab
    kernel = kernel.astype(feats.dtype)
    if padded != vocab:
        kernel = jnp.pad(kernel, ((0, 0), (0, padded - vocab)))
    # (V-major) -> (block index, d, block_vocab): column i*bv + j of the
    # original kernel lands at [i, :, j].
    blocks = kernel.reshape(d, n_blocks, block_vocab).transpose(1, 0, 2)
    starts = jnp.arange(n_blocks, dtype=jnp.int32) * block_vocab

    def body(carry, xs):
        run_max, run_sum, label_logit = carry
        w, start = xs
        logits = jnp.dot(feats, w, preferred_element_type=jnp.float32)
        col = start + jnp.arange(block_vocab, dtype=jnp.int32)
        logits = jnp.where(col[None, :] < vocab, logits, -jnp.inf)
        block_max = jnp.max(logits, axis=-1)
        new_max = jnp.maximum(run_max, block_max)
        run_sum = run_sum * jnp.exp(run_max - new_max) + jnp.sum(
            jnp.exp(logits - new_max[:, None]), axis=-1
        )
        local = labels - start
        hit = (local >= 0) & (local < block_vocab)
        picked = jnp.take_along_axis(
            logits, jnp.clip(local, 0, block_vocab - 1)[:, None], axis=1
        )[:, 0]
        label_logit = label_logit + jnp.where(hit, picked, 0.0)
        return (new_max, run_sum, label_logit), None

    init = (
        jnp.full((n_tokens,), -jnp.inf, jnp.float32),
        jnp.zeros((n_tokens,), jnp.float32),
        jnp.zeros((n_tokens,), jnp.float32),
    )
    # checkpoint: the backward recomputes each block's logits from (feats,
    # w) instead of saving them — without it, scan stores every block's
    # logits as residuals and the full tensor is back.
    (run_max, run_sum, label_logit), _ = jax.lax.scan(
        jax.checkpoint(body), init, (blocks, starts)
    )
    label_logit = jnp.where(valid, label_logit, jnp.nan)
    lse = run_max + jnp.log(run_sum)
    return (lse - label_logit, lse) if return_lse else lse - label_logit
