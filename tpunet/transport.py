"""Pythonic wrapper over the tpunet C ABI (point-to-point transport).

Maps the reference's C++ singleton wrapper role (reference: cc/bagua_net.h
class BaguaNet) into Python, with the buffer-lifetime hazard handled
explicitly: every in-flight request pins a reference to its buffer until
``test()`` reports done, so the GC cannot free memory the native stream
workers are still reading/writing (SURVEY hard-part #3; reference fabricated
'static slices and relied on NCCL, src/lib.rs:251,279).
"""

from __future__ import annotations

import ctypes
import time
from typing import Any

import numpy as np

from tpunet import _native


def fault_inject(spec: str) -> None:
    """Arm a deterministic transport fault process-wide (chaos testing).

    ``spec`` uses the native grammar, e.g. ``"stream=1:after_bytes=1M:
    action=close"`` — see docs/DESIGN.md "Failure model" for the full
    vocabulary (close / stall / corrupt / delay=<ms>). One fault at a time;
    re-arming replaces it and resets the byte counters. Raises NativeError
    (INVALID) naming the bad token for a malformed spec. The env knob
    TPUNET_FAULT_SPEC arms the same slot at engine creation."""
    lib = _native.load()
    _native.check(lib.tpunet_c_fault_inject(spec.encode()), "fault_inject")


def fault_clear() -> None:
    """Disarm any injected fault (safe to call when none is armed)."""
    lib = _native.load()
    _native.check(lib.tpunet_c_fault_clear(), "fault_clear")


def crc32c(data: Any, seed: int = 0) -> int:
    """CRC32C (Castagnoli) of a bytes-like object via the native library —
    the same routine that integrity-protects wire chunks under TPUNET_CRC=1.
    Chain calls by passing the previous value as ``seed``."""
    lib = _native.load()
    mv = memoryview(data)
    if not mv.c_contiguous:
        raise ValueError("crc32c needs a C-contiguous buffer")
    buf = bytes(mv) if mv.nbytes else b""
    return int(lib.tpunet_c_crc32c(buf, mv.nbytes, seed & 0xFFFFFFFF))


_REDUCE_DTYPES = {"f32": 0, "f64": 1, "bf16": 2, "i32": 3, "i64": 4, "u8": 5}
_REDUCE_OPS = {"sum": 0, "prod": 1, "min": 2, "max": 3}


def reduce_into(dst: np.ndarray, a: np.ndarray, b: np.ndarray, dtype: str,
                op: str = "sum") -> None:
    """Elementwise ``dst = a op b`` via the native reduction kernel — the
    runtime-dispatched (SIMD where the CPU has it) routine the ring
    collectives run post-wire. ``dst`` may be the same array as ``a``
    (in-place accumulate). ``dtype`` is the WIRE dtype ("f32", "f64",
    "bf16", "i32", "i64", "u8"); bf16 arrays are passed as uint16 views.
    Exposed so tests can pin SIMD-vs-scalar equivalence goldens."""
    if dtype not in _REDUCE_DTYPES:
        raise ValueError(f"unknown reduce dtype {dtype!r}")
    if op not in _REDUCE_OPS:
        raise ValueError(f"unknown reduce op {op!r}")
    for name, arr, writable in (("dst", dst, True), ("a", a, False), ("b", b, False)):
        if not isinstance(arr, np.ndarray) or not arr.flags.c_contiguous:
            raise ValueError(f"{name} must be a C-contiguous numpy array")
        if writable and not arr.flags.writeable:
            raise ValueError(f"{name} must be writable")
    if not (dst.size == a.size == b.size):
        raise ValueError("dst/a/b element counts differ")
    lib = _native.load()
    _native.check(
        lib.tpunet_c_reduce(dst.ctypes.data, a.ctypes.data, b.ctypes.data,
                            dst.size, _REDUCE_DTYPES[dtype], _REDUCE_OPS[op]),
        "reduce",
    )


_CODECS = {"f32": 0, "bf16": 1, "int8": 2}

TRAFFIC_CLASSES = ("latency", "bulk", "control")


def qos_state() -> dict:
    """Parsed view of the process QoS scheduler's config + live state
    (weights, admission budgets, wire window, in-flight bytes) via
    ``tpunet_c_qos_state`` — lets tests and operators pin that
    ``TPUNET_QOS_WEIGHTS`` / ``TPUNET_QOS_INFLIGHT_BYTES`` parsed to what
    they meant. Keys: weights/budgets/admitted/queued ({class: int}),
    wire_window, wire_inflight (ints)."""
    lib = _native.load()
    buf = ctypes.create_string_buffer(4096)
    n = lib.tpunet_c_qos_state(buf, 4096)
    if n < 0:
        raise _native.NativeError(n, "qos_state")
    out: dict = {}
    for line in buf.value.decode().splitlines():
        parts = line.split()
        if not parts:
            continue
        if "=" in (parts[1] if len(parts) > 1 else ""):
            out[parts[0]] = {k: int(v) for k, v in
                             (kv.split("=") for kv in parts[1:])}
        elif len(parts) == 2:
            out[parts[0]] = int(parts[1])
    return out


def qos_drr_golden(weights: str, window: str, chunks: str) -> list[str]:
    """Deficit-round-robin arithmetic golden: the exact wire-credit grant
    order the QoS scheduler would produce for ``chunks``
    ("class:bytes,...", queued in order; completions retire in grant
    order) under ``weights`` (TPUNET_QOS_WEIGHTS grammar) and ``window``
    ("wire=<bytes>"). Pure arithmetic — no sockets — so tests can pin
    strict control priority and the weighted latency/bulk interleave.
    Malformed specs raise NativeError (INVALID) naming the token."""
    lib = _native.load()
    buf = ctypes.create_string_buffer(65536)
    n = lib.tpunet_c_qos_drr_golden(weights.encode(), window.encode(),
                                    chunks.encode(), buf, 65536)
    _native.check(min(n, 0), "qos_drr_golden")
    return buf.value.decode().split(",") if buf.value else []


def lane_parse(spec: str) -> list[dict]:
    """Parse a ``TPUNET_LANES`` spec through the native parser — the same
    grammar the engines consume (``"addr=10.0.0.1:w=4,addr=10.0.1.1:w=1"``;
    a lane may omit either key). Returns one ``{"lane", "addr", "w"}`` dict
    per lane (``addr`` is ``None`` for the default path). Malformed specs
    raise NativeError (INVALID) naming the offending token, so
    ``Config.from_env`` and the native layer can never disagree on what a
    spec means. docs/DESIGN.md "Lanes & adaptive striping"."""
    lib = _native.load()
    buf = ctypes.create_string_buffer(16384)
    n = lib.tpunet_c_lane_parse(spec.encode(), buf, 16384)
    _native.check(min(n, 0), "lane_parse")
    out = []
    for line in buf.value.decode().splitlines():
        kv = dict(tok.split("=", 1) for tok in line.split())
        out.append({"lane": int(kv["lane"]),
                    "addr": None if kv["addr"] == "-" else kv["addr"],
                    "w": int(kv["w"])})
    return out


def stripe_map(length: int, min_chunksize: int, weights: list[int] | tuple[int, ...],
               cursor: int = 0) -> list[int]:
    """Chunk→stream assignment a message of ``length`` bytes gets under the
    weighted stripe scheduler (one entry per chunk), via
    ``tpunet_c_stripe_map`` — EXACTLY the arithmetic both engines run, so
    golden tests can pin that sender and receiver derive identical layouts
    from ``(len, min_chunksize, weights[epoch])`` alone with no layout
    metadata on the wire. Equal weights reproduce the pre-lane uniform
    rotation ``(cursor + i) % nstreams``."""
    lib = _native.load()
    wspec = ",".join(str(int(w)) for w in weights)
    # Two-call sizing (the tpunet_c_metrics_text contract): probe the text
    # length, then read it exactly — a dense map over a big grid can be long.
    n = lib.tpunet_c_stripe_map(length, min_chunksize, wspec.encode(), cursor,
                                None, 0)
    _native.check(min(n, 0), "stripe_map")
    buf = ctypes.create_string_buffer(n + 1)
    n = lib.tpunet_c_stripe_map(length, min_chunksize, wspec.encode(), cursor,
                                buf, n + 1)
    _native.check(min(n, 0), "stripe_map")
    return [int(t) for t in buf.value.decode().split(",")] if buf.value else []


def codec_wire_bytes(codec: str, n: int) -> int:
    """Encoded byte count for ``n`` f32 elements under ``codec`` ("f32",
    "bf16" or "int8") — the exact sizing rule the compressed ring uses
    (bf16: 2n; int8: n + 4*ceil(n/256) for the per-block f32 scales)."""
    if codec not in _CODECS:
        raise ValueError(f"unknown wire codec {codec!r}")
    lib = _native.load()
    return int(lib.tpunet_c_codec_wire_bytes(_CODECS[codec], n))


def codec_encode(arr: np.ndarray, codec: str) -> np.ndarray:
    """Encode a C-contiguous float32 array into its wire form (uint8 array)
    via the native codec kernel — the SAME routine the ring collectives run
    before every compressed isend, exposed so golden tests can pin the wire
    format (bf16 RNE incl. NaN/inf/-0.0; int8 block-scale layout and error
    bound) without a socket in sight."""
    if codec not in _CODECS:
        raise ValueError(f"unknown wire codec {codec!r}")
    if not isinstance(arr, np.ndarray) or arr.dtype != np.float32 or not arr.flags.c_contiguous:
        raise ValueError("codec_encode needs a C-contiguous float32 array")
    lib = _native.load()
    out = np.empty(codec_wire_bytes(codec, arr.size), np.uint8)
    _native.check(
        lib.tpunet_c_codec_encode(_CODECS[codec], arr.ctypes.data, arr.size,
                                  out.ctypes.data if out.size else None, out.size),
        "codec_encode",
    )
    return out


def codec_decode(wire: np.ndarray, codec: str, n: int) -> np.ndarray:
    """Decode a wire buffer of ``n`` encoded f32 elements back to float32 —
    the fused decode half of the ring's post-irecv stage (without the
    reduce)."""
    if codec not in _CODECS:
        raise ValueError(f"unknown wire codec {codec!r}")
    wire = np.ascontiguousarray(wire, np.uint8)
    if wire.size != codec_wire_bytes(codec, n):
        raise ValueError(
            f"wire buffer is {wire.size}B but {codec} x {n} elements encodes to "
            f"{codec_wire_bytes(codec, n)}B"
        )
    lib = _native.load()
    out = np.empty(n, np.float32)
    _native.check(
        lib.tpunet_c_codec_decode(_CODECS[codec], wire.ctypes.data if wire.size else None,
                                  n, out.ctypes.data if out.size else None),
        "codec_decode",
    )
    return out


def _as_buffer(obj: Any, writable: bool) -> tuple[int, int, Any]:
    """Return (address, nbytes, pin) for bytes/bytearray/numpy/memoryview."""
    if isinstance(obj, np.ndarray):
        if writable and not obj.flags.writeable:
            raise ValueError("recv buffer must be writable")
        if not obj.flags.c_contiguous:
            raise ValueError("buffer must be C-contiguous")
        return obj.ctypes.data, obj.nbytes, obj
    mv = memoryview(obj)
    if writable and mv.readonly:
        raise ValueError("recv buffer must be writable")
    if not mv.c_contiguous:
        raise ValueError("buffer must be C-contiguous")
    c = (ctypes.c_char * mv.nbytes).from_buffer(mv) if not mv.readonly else (
        ctypes.c_char * mv.nbytes).from_buffer_copy(mv)
    return ctypes.addressof(c), mv.nbytes, (c, mv)


class Request:
    """In-flight isend/irecv; poll with test(), or wait()."""

    def __init__(self, net: "Net", req_id: int, pin: Any):
        self._net = net
        self._id = req_id
        self._pin = pin  # keeps the buffer alive until done
        self._done = False
        self._nbytes = 0

    def test(self) -> tuple[bool, int]:
        if self._done:
            return True, self._nbytes
        lib = self._net._lib
        done = ctypes.c_uint8(0)
        nbytes = ctypes.c_uint64(0)
        _native.check(
            lib.tpunet_c_test(self._net._id, self._id, ctypes.byref(done), ctypes.byref(nbytes)),
            "test",
        )
        if done.value:
            self._done = True
            self._nbytes = nbytes.value
            self._pin = None  # release the buffer pin
        return self._done, self._nbytes

    def wait(self, timeout: float | None = None) -> int:
        if self._done:
            return self._nbytes
        if timeout is None:
            # True blocking wait in native code: ctypes releases the GIL for
            # the call and the condvar park costs no CPU — a Python poll loop
            # here would compete with the stream worker threads for cores.
            lib = self._net._lib
            nbytes = ctypes.c_uint64(0)
            _native.check(
                lib.tpunet_c_wait(self._net._id, self._id, ctypes.byref(nbytes)),
                "wait",
            )
            self._done = True
            self._nbytes = nbytes.value
            self._pin = None
            return self._nbytes
        deadline = time.monotonic() + timeout
        polls = 0
        while True:
            done, nbytes = self.test()
            if done:
                return nbytes
            if time.monotonic() > deadline:
                raise TimeoutError(f"request {self._id} not done within {timeout}s")
            polls += 1
            if polls > 200:
                time.sleep(min(1e-3, 1e-5 * (polls - 200)))


class SendComm:
    def __init__(self, net: "Net", comm_id: int):
        self._net = net
        self._id = comm_id

    def isend(self, buf: Any) -> Request:
        addr, nbytes, pin = _as_buffer(buf, writable=False)
        req = ctypes.c_size_t(0)
        _native.check(
            self._net._lib.tpunet_c_isend(self._net._id, self._id, addr, nbytes, ctypes.byref(req)),
            "isend",
        )
        return Request(self._net, req.value, pin)

    def send(self, buf: Any, timeout: float | None = None) -> int:
        return self.isend(buf).wait(timeout)

    def close(self) -> None:
        _native.check(self._net._lib.tpunet_c_close_send(self._net._id, self._id), "close_send")


class RecvComm:
    def __init__(self, net: "Net", comm_id: int):
        self._net = net
        self._id = comm_id

    def irecv(self, buf: Any) -> Request:
        addr, nbytes, pin = _as_buffer(buf, writable=True)
        req = ctypes.c_size_t(0)
        _native.check(
            self._net._lib.tpunet_c_irecv(self._net._id, self._id, addr, nbytes, ctypes.byref(req)),
            "irecv",
        )
        return Request(self._net, req.value, pin)

    def recv(self, buf: Any, timeout: float | None = None) -> int:
        return self.irecv(buf).wait(timeout)

    def close(self) -> None:
        _native.check(self._net._lib.tpunet_c_close_recv(self._net._id, self._id), "close_recv")


class ListenComm:
    def __init__(self, net: "Net", comm_id: int, handle: bytes):
        self._net = net
        self._id = comm_id
        self.handle = handle  # 64-byte rendezvous blob, ship out-of-band

    def accept(self) -> RecvComm:
        rid = ctypes.c_size_t(0)
        _native.check(
            self._net._lib.tpunet_c_accept(self._net._id, self._id, ctypes.byref(rid)), "accept"
        )
        return RecvComm(self._net, rid.value)

    def close(self) -> None:
        _native.check(self._net._lib.tpunet_c_close_listen(self._net._id, self._id), "close_listen")


class Net:
    """One transport engine instance (reference: BaguaNet singleton — but
    multiple instances are allowed here).

    ``traffic_class`` ("latency" / "bulk" / "control") pins the QoS lane
    every comm this engine CONNECTS will carry — the class nibble rides the
    connect preamble, so the far side's recv comm adopts it (sender's class
    wins, like nstreams). None defers to TPUNET_TRAFFIC_CLASS (default
    bulk). docs/DESIGN.md "Transport QoS"."""

    def __init__(self, traffic_class: str | None = None) -> None:
        if traffic_class is not None and traffic_class not in TRAFFIC_CLASSES:
            raise ValueError(
                f"traffic_class must be one of {TRAFFIC_CLASSES}, "
                f"got {traffic_class!r}")
        self._lib = _native.load()
        inst = ctypes.c_size_t(0)
        _native.check(
            self._lib.tpunet_c_create_ex(
                (traffic_class or "").encode(), ctypes.byref(inst)),
            "create",
        )
        self._id = inst.value
        self.traffic_class = traffic_class

    def devices(self) -> int:
        n = ctypes.c_int32(0)
        _native.check(self._lib.tpunet_c_devices(self._id, ctypes.byref(n)), "devices")
        return n.value

    def properties(self, dev: int = 0) -> dict:
        p = _native.NetProperties()
        _native.check(self._lib.tpunet_c_get_properties(self._id, dev, ctypes.byref(p)), "props")
        return {
            "name": (p.name or b"").decode(),
            "pci_path": (p.pci_path or b"").decode(),
            "guid": p.guid,
            "ptr_support": p.ptr_support,
            "speed_mbps": p.speed_mbps,
            "port": p.port,
            "max_comms": p.max_comms,
        }

    def listen(self, dev: int = 0) -> ListenComm:
        h = _native.SocketHandle()
        lid = ctypes.c_size_t(0)
        _native.check(
            self._lib.tpunet_c_listen(self._id, dev, ctypes.byref(h), ctypes.byref(lid)), "listen"
        )
        return ListenComm(self, lid.value, bytes(h.data))

    def connect(self, handle: bytes, dev: int = 0) -> SendComm:
        if len(handle) != _native.HANDLE_SIZE:
            raise ValueError(f"handle must be {_native.HANDLE_SIZE} bytes")
        h = _native.SocketHandle()
        ctypes.memmove(h.data, handle, _native.HANDLE_SIZE)
        sid = ctypes.c_size_t(0)
        _native.check(
            self._lib.tpunet_c_connect(self._id, dev, ctypes.byref(h), ctypes.byref(sid)), "connect"
        )
        return SendComm(self, sid.value)

    def close(self) -> None:
        if self._id:
            inst = ctypes.c_size_t(self._id)
            self._id = 0
            _native.check(self._lib.tpunet_c_destroy(ctypes.byref(inst)), "destroy")

    def __enter__(self) -> "Net":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
