"""Parallelism layer: device meshes, sharding rules, and the DP/TP/SP
building blocks for multi-chip training.

In-pod (ICI) parallelism is expressed through `jax.sharding` — pick a mesh,
annotate shardings, let XLA insert the collectives. Cross-host (DCN)
parallelism rides the tpunet transport via `tpunet.interop`. This split
mirrors the reference stack, where NCCL handled intra-node NVLink and the
reference plugin carried the inter-node TCP traffic (SURVEY §5).
"""

from tpunet.parallel.mesh import (  # noqa: F401
    batch_sharding,
    make_mesh,
    make_named_mesh,
    replicated,
    shard_params,
    vgg_partition_rules,
)
from tpunet.parallel.dcn_ring_attention import (  # noqa: F401
    dcn_ring_attention,
    dcn_zigzag_attention,
)
from tpunet.parallel.pipeline import (  # noqa: F401
    gpipe,
    stack_stage_params,
)
from tpunet.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ring_self_attention,
)
from tpunet.parallel.zigzag_attention import (  # noqa: F401
    from_zigzag,
    to_zigzag,
    zigzag_positions,
    zigzag_ring_attention,
    zigzag_self_attention,
)
from tpunet.parallel.ulysses import (  # noqa: F401
    dcn_ulysses_attention,
    ulysses_attention,
    ulysses_self_attention,
)
