"""Cross-host ring attention: the sequence ring spans PROCESSES over DCN.

Two-tier long-context story (mirrors the transport/collectives split):
  * in-pod — `tpunet.parallel.ring_attention`: sp mesh axis, k/v rotate via
    `lax.ppermute` over ICI at interconnect speed.
  * cross-host (this module) — the sequence dimension is sharded across
    processes; k/v blocks rotate through the process ring via the
    multi-stream DCN transport (`Communicator.neighbor_exchange`, entering
    jit through `io_callback`), and the same online-softmax recurrence folds
    one block per step.

Together they let context length scale with the whole pod-slice *and* across
pods/hosts — the capability the task brief requires to be first-class, built
directly on the framework's own transport (the reference repo has neither
attention nor any model layer; SURVEY §5 "long-context: absent").

The per-step block math is shared with the ICI version (`_block_update`), so
the two tiers cannot drift numerically.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from tpunet.parallel.ring_attention import NEG_INF, _block_update


def _exchange_packed(kc, vc):
    """Ring-shift k and v in ONE neighbor exchange (concat on the last
    axis — they share (batch, seq, heads)). A single collective per
    rotation keeps the cross-rank call sequence trivially aligned even
    though each rank traces a different (rank-constant-bearing) program."""
    from tpunet.interop import dcn_neighbor_exchange

    dk = kc.shape[-1]
    wide = jnp.promote_types(kc.dtype, vc.dtype)  # lossless packing
    packed = dcn_neighbor_exchange(
        jnp.concatenate([kc.astype(wide), vc.astype(wide)], axis=-1))
    return packed[..., :dk].astype(kc.dtype), packed[..., dk:].astype(vc.dtype)


def dcn_ring_attention(q, k, v, causal: bool = False):
    """Ring attention across processes. q/k/v: this process's sequence shard
    (batch, s_local, heads, head_dim); every process must hold equal-length
    shards in rank order. Jittable. The per-rotation k/v shift is ONE
    packed collective: on the FFI custom-call path (default on CPU),
    data-independent collectives in this rank-asymmetric trace carry no
    cross-rank ordering guarantee — anyone adding another collective here
    must pack it in or pin it with `after=` (tpunet.interop docstring).
    Requires `tpunet.distributed.initialize()` before the first trace."""
    from tpunet import distributed
    w = distributed.world_size()
    my = distributed.rank()
    s_local = q.shape[1]
    scale = 1.0 / math.sqrt(q.shape[-1])

    acc = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    m = jnp.full(q.shape[:3] + (1,), NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3] + (1,), jnp.float32)

    kc, vc = k, v
    # Unrolled at trace time (w is static). Step t folds in the block that
    # originated at rank (my - t) mod w; blocks travel rank -> rank+1.
    for t in range(w):
        src = (my - t) % w
        if causal and src > my:
            # Fully-masked future block: the exchange must still happen (the
            # ring is collective) but the einsums are skipped — and since
            # src/my are Python ints here, the skip costs nothing at trace
            # time (the ICI tier needs a lax.switch for the same schedule).
            pass
        else:
            # Strictly-past blocks (src < my) are entirely unmasked — only
            # the diagonal needs the elementwise causal mask. Free at trace
            # time (src/my are Python ints), mirroring the ICI tier's
            # full/diag split.
            acc, m, l = _block_update(
                q, kc, vc, acc, m, l,
                q_start=my * s_local, k_start=src * s_local,
                causal=causal and src == my, scale=scale,
            )
        if t + 1 < w:
            # ONE packed exchange, not one per tensor: data-independent
            # FFI collectives carry no cross-rank ordering guarantee when
            # per-rank programs differ (this trace bakes in rank), and the
            # packed form also halves the per-rotation message count.
            kc, vc = _exchange_packed(kc, vc)
    return (acc / l).astype(q.dtype)


def dcn_zigzag_attention(q, k, v):
    """Cross-host ZIGZAG causal attention: the balanced-schedule sibling of
    `dcn_ring_attention`, mirroring the ICI pair
    (`ring_attention`/`zigzag_ring_attention`). Each process holds sequence
    chunks (rank, 2W-1-rank) of a `to_zigzag`-permuted global sequence, so
    every process does ~the same causal work per ring step instead of the
    last rank carrying W full blocks. The whole schedule is TRACE-TIME
    static here (rank/world are Python ints), so skipped chunk-pairs emit no
    ops at all. Causal only — that is the imbalance being fixed.

    q/k/v: (batch, 2c, heads, head_dim), this process's zigzag chunk pair.
    Positions for rotary: `zigzag_positions(world, world*2c, rank)`.
    """
    from tpunet import distributed

    w = distributed.world_size()
    my = distributed.rank()
    if q.shape[1] % 2:
        raise ValueError("zigzag shard length must be even (a chunk pair)")
    c = q.shape[1] // 2
    scale = 1.0 / math.sqrt(q.shape[-1])

    def _init(qh):
        return (
            jnp.zeros(qh.shape[:3] + (v.shape[-1],), jnp.float32),
            jnp.full(qh.shape[:3] + (1,), NEG_INF, jnp.float32),
            jnp.zeros(qh.shape[:3] + (1,), jnp.float32),
        )

    q_lo, q_hi = q[:, :c], q[:, c:]
    st_lo, st_hi = _init(q_lo), _init(q_hi)
    kc, vc = k, v
    for t in range(w):
        src = (my - t) % w  # holder of chunks (src, 2w-1-src) this step
        k_lo, v_lo = kc[:, :c], vc[:, :c]
        k_hi, v_hi = kc[:, c:], vc[:, c:]
        # a_hi x b_lo: always a full unmasked block (b_lo < W <= a_hi).
        st_hi = _block_update(q_hi, k_lo, v_lo, *st_hi, 0, 0,
                              causal=False, scale=scale)
        # a_lo x b_lo: full iff src < my, diagonal iff equal, else nothing.
        if src < my:
            st_lo = _block_update(q_lo, k_lo, v_lo, *st_lo, 0, 0,
                                  causal=False, scale=scale)
        elif src == my:
            st_lo = _block_update(q_lo, k_lo, v_lo, *st_lo, 0, 0,
                                  causal=True, scale=scale)
        # a_hi x b_hi: chunk order reverses — full iff src > my.
        if src > my:
            st_hi = _block_update(q_hi, k_hi, v_hi, *st_hi, 0, 0,
                                  causal=False, scale=scale)
        elif src == my:
            st_hi = _block_update(q_hi, k_hi, v_hi, *st_hi, 0, 0,
                                  causal=True, scale=scale)
        # (a_lo x b_hi never computes: b_hi >= W > a_lo.)
        if t + 1 < w:
            kc, vc = _exchange_packed(kc, vc)  # see dcn_ring_attention
    out = jnp.concatenate(
        [st_lo[0] / st_lo[2], st_hi[0] / st_hi[2]], axis=1
    )
    return out.astype(q.dtype)
