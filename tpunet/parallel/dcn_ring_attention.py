"""Cross-host ring attention: the sequence ring spans PROCESSES over DCN.

Two-tier long-context story (mirrors the transport/collectives split):
  * in-pod — `tpunet.parallel.ring_attention`: sp mesh axis, k/v rotate via
    `lax.ppermute` over ICI at interconnect speed.
  * cross-host (this module) — the sequence dimension is sharded across
    processes; k/v blocks rotate through the process ring via the
    multi-stream DCN transport (`Communicator.neighbor_exchange`, entering
    jit through `io_callback`), and the same online-softmax recurrence folds
    one block per step.

Together they let context length scale with the whole pod-slice *and* across
pods/hosts — the capability the task brief requires to be first-class, built
directly on the framework's own transport (the reference repo has neither
attention nor any model layer; SURVEY §5 "long-context: absent").

The per-step block math is shared with the ICI version (`_block_update`), so
the two tiers cannot drift numerically.
"""

from __future__ import annotations

import math

import jax.numpy as jnp

from tpunet.parallel.ring_attention import NEG_INF, _block_update


def dcn_ring_attention(q, k, v, causal: bool = False):
    """Ring attention across processes. q/k/v: this process's sequence shard
    (batch, s_local, heads, head_dim); every process must hold equal-length
    shards in rank order. Jittable (the exchanges are ordered io_callbacks).
    Requires `tpunet.distributed.initialize()` before the first trace."""
    from tpunet import distributed
    from tpunet.interop import dcn_neighbor_exchange

    w = distributed.world_size()
    my = distributed.rank()
    s_local = q.shape[1]
    scale = 1.0 / math.sqrt(q.shape[-1])

    acc = jnp.zeros(q.shape[:3] + (v.shape[-1],), jnp.float32)
    m = jnp.full(q.shape[:3] + (1,), NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3] + (1,), jnp.float32)

    kc, vc = k, v
    # Unrolled at trace time (w is static). Step t folds in the block that
    # originated at rank (my - t) mod w; blocks travel rank -> rank+1.
    for t in range(w):
        src = (my - t) % w
        if causal and src > my:
            # Fully-masked future block: the exchange must still happen (the
            # ring is collective) but the einsums are skipped — and since
            # src/my are Python ints here, the skip costs nothing at trace
            # time (the ICI tier needs a lax.switch for the same schedule).
            pass
        else:
            # Strictly-past blocks (src < my) are entirely unmasked — only
            # the diagonal needs the elementwise causal mask. Free at trace
            # time (src/my are Python ints), mirroring the ICI tier's
            # full/diag split.
            acc, m, l = _block_update(
                q, kc, vc, acc, m, l,
                q_start=my * s_local, k_start=src * s_local,
                causal=causal and src == my, scale=scale,
            )
        if t + 1 < w:
            kc = dcn_neighbor_exchange(kc)
            vc = dcn_neighbor_exchange(vc)
    return (acc / l).astype(q.dtype)
