"""Zigzag (striped) causal ring attention — load-balanced context parallelism.

Contiguous-shard ring attention is causally imbalanced: rank i's queries can
attend only i+1 of the W K/V shards, yet the ring takes W lockstep steps, so
the LAST rank computes a full unmasked block every step (the critical path)
while early ranks mostly produce fully-masked blocks. The zigzag layout
fixes this: split the sequence into 2W chunks and give device i the PAIR
(i, 2W-1-i) — one early chunk, one late chunk. Then at every ring step each
device has ~the same causal work:

  per step, with local q chunks (a_lo=i, a_hi=2W-1-i) and the held K/V pair
  (b_lo=s, b_hi=2W-1-s):
    a_lo x b_hi : NEVER computes (b_hi >= W > a_lo)          — static skip
    a_hi x b_lo : ALWAYS a full unmasked block (b_lo < W <= a_hi)
    a_lo x b_lo : full iff s < i, diagonal iff s == i         — lax.switch
    a_hi x b_hi : full iff s > i, diagonal iff s == i         — lax.switch

  => ~2 chunk-blocks of work per device per step (vs 4 for the contiguous
  layout's full local block), balanced across ranks: the causal critical
  path halves. This is the striped/zigzag schedule of context-parallel
  training (public "striped attention" recipe), expressed as compiler-
  friendly lax primitives — the skips are trace-time structure or a scalar
  lax.switch, never data-dependent Python.

The trade: callers must hold the sequence in zigzag order end-to-end
(`to_zigzag` / `from_zigzag`), and position-dependent layers (rotary) must
use zigzag positions (`zigzag_positions`). The reference repo has no
attention at all (SURVEY §5 "long-context: absent"); this is the
load-balanced upgrade over tpunet's own contiguous ring.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpunet.parallel.ring_attention import (NEG_INF, _block_update,
                                            causal_block_mode,
                                            switched_block_update)
from tpunet.parallel.smap import full_varying, shard_map, vma_of


def zigzag_chunk_order(world: int) -> list[int]:
    """Global chunk order of the zigzag layout: device i holds chunks
    (i, 2W-1-i), laid out as [0, 2W-1, 1, 2W-2, ...]."""
    order: list[int] = []
    for i in range(world):
        order.extend((i, 2 * world - 1 - i))
    return order


def to_zigzag(x, world: int, axis: int = 1):
    """Permute a (…, seq, …) array from natural to zigzag chunk order so a
    contiguous sp-sharding hands each device its zigzag pair."""
    seq = x.shape[axis]
    if seq % (2 * world):
        raise ValueError(f"seq {seq} must divide into 2*world={2 * world} chunks")
    chunks = jnp.split(x, 2 * world, axis=axis)
    return jnp.concatenate([chunks[c] for c in zigzag_chunk_order(world)], axis=axis)


def from_zigzag(x, world: int, axis: int = 1):
    """Inverse of to_zigzag."""
    order = zigzag_chunk_order(world)
    inverse = [0] * len(order)
    for pos, c in enumerate(order):
        inverse[c] = pos
    chunks = jnp.split(x, 2 * world, axis=axis)
    return jnp.concatenate([chunks[p] for p in inverse], axis=axis)


def zigzag_positions(world: int, seq: int, device_index):
    """Global token positions of device `device_index`'s local shard (length
    seq//world), for position-dependent layers (rotary) under the zigzag
    layout. device_index may be traced (e.g. lax.axis_index)."""
    c = seq // (2 * world)
    lo = device_index * c + jnp.arange(c, dtype=jnp.int32)
    hi = (2 * world - 1 - device_index) * c + jnp.arange(c, dtype=jnp.int32)
    return jnp.concatenate([lo, hi])


def zigzag_ring_attention(q, k, v, axis_name: str):
    """Per-shard zigzag causal ring attention; call inside shard_map.

    q/k/v: this device's zigzag shard, (batch, 2c, heads, head_dim) — the
    concatenation of chunks i and 2W-1-i of a to_zigzag()-permuted sequence.
    Returns the local shard of the attention output (same layout). Causal
    only: the whole point is balancing the causal mask; use ring_attention
    for the non-causal case (already balanced).
    """
    w = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    c = q.shape[1] // 2
    if q.shape[1] % 2:
        raise ValueError("zigzag shard length must be even (a chunk pair)")
    scale = 1.0 / math.sqrt(q.shape[-1])
    perm = [(i, (i + 1) % w) for i in range(w)]
    vma = vma_of(q)

    q_lo, q_hi = q[:, :c], q[:, c:]

    def _init_state(qh):
        shape = qh.shape[:3]
        return (
            full_varying(shape + (v.shape[-1],), 0.0, jnp.float32, vma),
            full_varying(shape + (1,), NEG_INF, jnp.float32, vma),
            full_varying(shape + (1,), 0.0, jnp.float32, vma),
        )

    def body(carry, t):
        k_cur, v_cur, st_lo, st_hi = carry
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (my - t) % w  # holder of chunks (src, 2w-1-src) this step
        k_lo, v_lo = k_cur[:, :c], v_cur[:, :c]
        k_hi, v_hi = k_cur[:, c:], v_cur[:, c:]

        # a_hi x b_lo: statically always a full unmasked block.
        acc, m, l = st_hi
        st_hi = _block_update(q_hi, k_lo, v_lo, acc, m, l, 0, 0, causal=False,
                              scale=scale)
        # a_lo x b_lo: full iff src < my, diag iff src == my, else skip.
        st_lo = switched_block_update(q_lo, k_lo, v_lo, st_lo,
                                      causal_block_mode(src, my), scale)
        # a_hi x b_hi: chunk ids 2w-1-src vs 2w-1-my reverse the order —
        # full iff src > my, diag iff src == my, else skip.
        st_hi = switched_block_update(q_hi, k_hi, v_hi, st_hi,
                                      causal_block_mode(my, src), scale)
        # (a_lo x b_hi never computes: b_hi >= W > a_lo for every step.)
        return (k_nxt, v_nxt, st_lo, st_hi), None

    init = (k, v, _init_state(q_lo), _init_state(q_hi))
    (_, _, (acc_lo, _, l_lo), (acc_hi, _, l_hi)), _ = jax.lax.scan(
        body, init, jnp.arange(w)
    )
    out = jnp.concatenate([acc_lo / l_lo, acc_hi / l_hi], axis=1)
    return out.astype(q.dtype)


def zigzag_self_attention(
    q, k, v, mesh: Mesh,
    dp_axis: str | None = "dp", sp_axis: str = "sp", tp_axis: str | None = None,
):
    """Full-array entry point: q/k/v are (batch, seq, heads, head_dim)
    arrays ALREADY in zigzag order (to_zigzag), batch sharded over
    `dp_axis`, sequence over `sp_axis`, optional heads over `tp_axis`."""
    spec = P(dp_axis, sp_axis, tp_axis, None)
    fn = shard_map(
        partial(zigzag_ring_attention, axis_name=sp_axis),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return fn(q, k, v)
