"""Mesh + sharding helpers.

The canonical tpunet mesh has two axes:
  dp  — data parallelism: batch dimension sharded, params replicated.
  mdl — model (tensor) parallelism: big matmul kernels split Megatron-style
        (column-parallel then row-parallel); XLA inserts the all-reduces
        over ICI from the shardings alone.

Rules are path-regex → PartitionSpec, the standard JAX pattern (the public
scaling-book recipe: pick a mesh, annotate, let the compiler do the rest).
"""

from __future__ import annotations

import re
from collections.abc import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(dp: int | None = None, mdl: int = 1, devices=None) -> Mesh:
    """Build a (dp, mdl) mesh. dp defaults to n_devices/mdl."""
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if dp is None:
        if n % mdl != 0:
            raise ValueError(f"{n} devices not divisible by mdl={mdl}")
        dp = n // mdl
    if dp * mdl != n:
        raise ValueError(f"dp({dp}) * mdl({mdl}) != devices({n})")
    arr = np.array(devices).reshape(dp, mdl)
    return Mesh(arr, axis_names=("dp", "mdl"))


def make_named_mesh(axis_sizes: dict[str, int], devices=None) -> Mesh:
    """Build a mesh with arbitrary named axes, e.g. {"dp": 2, "tp": 2, "sp": 2}.

    Axis order is the dict order (outermost first — put the axis whose
    collectives are heaviest innermost so it maps to the fastest ICI links)."""
    devices = list(devices if devices is not None else jax.devices())
    sizes = tuple(axis_sizes.values())
    n = int(np.prod(sizes))
    if len(devices) < n:
        raise ValueError(f"need {n} devices for {axis_sizes}, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(sizes)
    return Mesh(arr, axis_names=tuple(axis_sizes.keys()))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (batch) axis over dp; everything else replicated."""
    return NamedSharding(mesh, P("dp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


# ---------------------------------------------------------------------------
# Parameter partition rules: list of (path_regex, PartitionSpec). First match
# wins; no match = replicated.

def vgg_partition_rules() -> list[tuple[str, P]]:
    """Megatron-style TP for the VGG classifier over the `mdl` axis:
    fc1 column-parallel (output dim sharded), fc2 row-parallel (input dim
    sharded, XLA all-reduces the partial sums), head column-parallel.
    Conv kernels stay replicated (they're small relative to the FCs —
    VGG16's fc1 alone is 25k x 4096 ≈ 100M params, ~2/3 of the model).
    """
    return [
        (r".*fc1/kernel", P(None, "mdl")),
        (r".*fc1/bias", P("mdl")),
        (r".*fc2/kernel", P("mdl", None)),
        (r".*head/kernel", P(None, "mdl")),
        (r".*head/bias", P("mdl")),
    ]


def _spec_for_path(path: str, rules: Sequence[tuple[str, P]]) -> P:
    for pattern, spec in rules:
        if re.fullmatch(pattern, path):
            return spec
    return P()


def shard_params(params, mesh: Mesh, rules: Sequence[tuple[str, P]] | None = None):
    """Tree of NamedShardings for a param pytree, keyed by the flax path."""
    rules = list(rules) if rules is not None else []

    def to_sharding(path, leaf):
        path_str = "/".join(getattr(k, "key", getattr(k, "name", str(k))) for k in path)
        spec = _spec_for_path(path_str, rules)
        # A spec axis must divide the dim; fall back to replication if the
        # tiny test config doesn't (e.g. width_mult shrinks fc1 below mdl).
        for dim, axis in enumerate(spec):
            if axis is None:
                continue
            axes = (axis,) if isinstance(axis, str) else tuple(axis)
            size = int(np.prod([mesh.shape[a] for a in axes]))
            if dim >= leaf.ndim or leaf.shape[dim] % size != 0:
                return NamedSharding(mesh, P())
        return NamedSharding(mesh, spec)

    return jax.tree_util.tree_map_with_path(to_sharding, params)
