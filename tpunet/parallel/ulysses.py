"""Ulysses sequence parallelism — all-to-all head/sequence re-sharding.

The second SP strategy next to ring attention (SURVEY §2.3 checklist): instead
of rotating K/V blocks around a ring, two all-to-alls re-shard the arrays so
each device sees the FULL sequence for a SUBSET of heads:

    (b, S/P, H, d) --all_to_all--> (b, S, H/P, d)   attention   --back-->

Attention itself then needs no communication at all (each head attends over
the whole sequence locally), which makes Ulysses the better choice when
head count >= devices and the interconnect favors few large collectives;
ring attention wins when S/P blocks overlap compute with permutes or when
H < P. Both tiers are provided:

  * in-pod (ICI): `ulysses_self_attention` — `lax.all_to_all` inside
    shard_map; XLA lowers it onto the ICI mesh.
  * cross-host (DCN): `dcn_ulysses_attention` — the transport's native
    store-and-forward AllToAll (`Communicator.all_to_all`) entering jit via
    `tpunet.interop.dcn_all_to_all`.

The reference repo has no attention layer (SURVEY §5 "long-context:
absent"); this is capability the TPU build makes first-class, riding the
framework's own AllToAll collective.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpunet.ops import attention_reference
from tpunet.parallel.smap import shard_map


def ulysses_attention(q, k, v, axis_name: str, causal: bool = False):
    """Per-shard Ulysses attention; call inside `shard_map` (or pmap).

    q/k/v: this device's sequence shard (batch, s_local, heads, head_dim),
    sequence sharded over `axis_name` in ring order, heads divisible by the
    axis size. Returns the local shard of the output, q-shaped.
    """
    w = jax.lax.psum(1, axis_name)
    h = q.shape[2]
    if h % w != 0:
        raise ValueError(f"heads {h} not divisible by '{axis_name}' size {w}")

    # seq-sharded -> head-sharded: split heads (axis 2) across the axis,
    # concatenate the received sequence chunks (axis 1) in device order —
    # which is global sequence order, so causal masking stays plain.
    def to_heads(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    o = attention_reference(to_heads(q), to_heads(k), to_heads(v), causal)
    # head-sharded -> seq-sharded: the inverse re-shard.
    return jax.lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2, tiled=True)


def ulysses_self_attention(
    q, k, v, mesh: Mesh, causal: bool = False,
    dp_axis: str | None = "dp", sp_axis: str = "sp", tp_axis: str | None = None,
):
    """Full-array entry point (mirror of `ring_self_attention`): q/k/v are
    (batch, seq, heads, head_dim) global arrays with batch over `dp_axis`,
    sequence over `sp_axis`, optionally heads over `tp_axis`."""
    spec = P(dp_axis, sp_axis, tp_axis, None)
    fn = shard_map(
        partial(ulysses_attention, axis_name=sp_axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return fn(q, k, v)


def dcn_ulysses_attention(q, k, v, causal: bool = False):
    """Ulysses attention across PROCESSES over the DCN transport.

    q/k/v: this process's sequence shard (batch, s_local, heads, head_dim) in
    rank order; heads divisible by world size. Jittable (the all-to-alls are
    data-DEPENDENT collectives — the second all-to-all consumes attention
    over the first's output, so their order is pinned by data flow on both
    the io_callback and FFI custom-call paths; an added independent
    collective would need `after=` — tpunet.interop docstring). Requires
    `tpunet.distributed.initialize()` before
    the first trace. Rotary/positions must already be global (the caller
    applies them with this process's sequence offset, exactly as for
    `dcn_ring_attention`)."""
    from tpunet import distributed
    from tpunet.interop import dcn_all_to_all

    w = distributed.world_size()
    if w == 1:
        return attention_reference(q, k, v, causal)
    b, s_local, h, d = q.shape
    if h % w != 0:
        raise ValueError(f"heads {h} not divisible by world size {w}")
    hl = h // w

    # One relay re-shards q, k, and v together — blocks (w, 3, b, sl, h/w, d),
    # head-group j to rank j. Three separate ordered relays would serialize
    # into 3*(W-1) latency-bound exchange rounds per layer; stacking moves
    # the same bytes in W-1.
    qkv = jnp.stack([q, k, v], axis=0)
    blocks = qkv.reshape(3, b, s_local, w, hl, d).transpose(3, 0, 1, 2, 4, 5)
    blocks = dcn_all_to_all(blocks)
    # received block j = rank j's sequence chunk of MY head group; ranks
    # hold contiguous chunks in rank order -> concat along seq.
    full = blocks.transpose(1, 2, 0, 3, 4, 5).reshape(3, b, w * s_local, hl, d)
    o = attention_reference(full[0], full[1], full[2], causal)

    # inverse: split full seq into per-rank chunks, all-to-all, reassemble
    # the original head order (block j = my sequence chunk of head-group j).
    blocks = o.reshape(b, w, s_local, hl, d).transpose(1, 0, 2, 3, 4)
    blocks = dcn_all_to_all(blocks)
    return blocks.transpose(1, 2, 0, 3, 4).reshape(b, s_local, h, d)
