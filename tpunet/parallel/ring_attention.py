"""Ring attention — sequence/context parallelism over a mesh axis.

Long-context support: the sequence dimension is sharded across the `sp` mesh
axis; each device keeps its local Q shard resident and the K/V shards rotate
around the ring via `lax.ppermute` while an online-softmax accumulator
(the flash-attention recurrence, f32) folds in one block per step. Peak
memory per device is O(S/W) activations and the score matrix never
materializes at full size — this is what lets sequence length scale with the
number of devices.

TPU mapping: the ppermute rides the ICI ring (or our DCN transport between
hosts via the interop tier); inside each step the block QK^T / PV matmuls are
MXU work. The permute for step t+1 is issued *before* the step-t block
compute, so XLA can overlap the collective-permute with the matmuls
(double-buffered ring — the standard TPU pattern).

The reference repo has no attention layer (SURVEY §5 "long-context: absent");
this module is the capability the task brief requires the TPU build to make
first-class, built on the same ring-topology insight as the transport's ring
collectives.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpunet.parallel.smap import full_varying, shard_map, vma_of

NEG_INF = -1e30


def _block_update(q, k, v, acc, m, l, q_start, k_start, causal: bool, scale: float):
    """Fold one K/V block into the online-softmax state.

    q: (b, sq, h, d); k/v: (b, sk, h, d); acc: (b, sq, h, d) f32;
    m/l: (b, sq, h, 1) f32. q_start/k_start are the *global* sequence
    offsets of the blocks (traced scalars are fine).
    """
    s = jnp.einsum(
        "bqhd,bkhd->bhqk",
        q.astype(jnp.float32) * scale,
        k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    )
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        qpos = q_start + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        kpos = k_start + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(qpos >= kpos, s, NEG_INF)
    # (b, h, q, k) -> row stats over k; keep (b, q, h, 1) layout for acc.
    m_blk = jnp.max(s, axis=-1).transpose(0, 2, 1)[..., None]
    m_new = jnp.maximum(m, m_blk)
    p = jnp.exp(s - m_new.squeeze(-1).transpose(0, 2, 1)[:, :, :, None])
    alpha = jnp.exp(m - m_new)
    l_new = alpha * l + jnp.sum(p, axis=-1).transpose(0, 2, 1)[..., None]
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                    preferred_element_type=jnp.float32)
    acc_new = acc * alpha + pv
    return acc_new, m_new, l_new


def causal_block_mode(k_chunk, q_chunk):
    """0=full (strictly past), 1=diagonal (same chunk), 2=skip (future),
    comparing chunk/block indices. Traced scalars are fine."""
    return jnp.where(k_chunk < q_chunk, 0, jnp.where(k_chunk == q_chunk, 1, 2))


def switched_block_update(q, k, v, state, mode, scale: float):
    """Fold one K/V block into the online-softmax `state` under a causal
    block schedule: `mode` selects a full unmasked update, a same-chunk
    diagonal update (offsets cancel, so 0/0 masks correctly), or a skip
    whose einsums never execute. Branches carry no collectives, so
    per-device divergence is SPMD-legal. Shared by the contiguous and
    zigzag ring schedules."""
    acc, m, l = state

    def full(_):
        return _block_update(q, k, v, acc, m, l, 0, 0, causal=False, scale=scale)

    def diag(_):
        return _block_update(q, k, v, acc, m, l, 0, 0, causal=True, scale=scale)

    def skip(_):
        return acc, m, l

    return jax.lax.switch(mode, (full, diag, skip), None)


def ring_attention(q, k, v, axis_name: str, causal: bool = False):
    """Per-shard ring attention; call inside `shard_map` (or pmap).

    q/k/v: this device's sequence shard, (batch, s_local, heads, head_dim),
    sequence sharded over `axis_name` in ring order. Returns the local shard
    of the attention output, q-shaped.
    """
    w = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    scale = 1.0 / math.sqrt(q.shape[-1])
    perm = [(i, (i + 1) % w) for i in range(w)]

    # The accumulators must carry q's varying-manual-axes type (jax >= 0.9
    # tracks vma through shard_map; a plain zeros literal is "unvarying" and
    # the scan carry types wouldn't match after the block update).
    vma = vma_of(q)

    def _init(shape, fill):
        return full_varying(shape, fill, jnp.float32, vma)

    acc0 = _init(q.shape[:3] + (v.shape[-1],), 0.0)
    m0 = _init(q.shape[:3] + (1,), NEG_INF)
    l0 = _init(q.shape[:3] + (1,), 0.0)

    def body(carry, t):
        k_cur, v_cur, acc, m, l = carry
        # Issue next-step permute BEFORE the block compute: no data dep
        # between them, so the collective overlaps the matmuls.
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (my - t) % w  # whose block we currently hold

        if causal:
            # Causal block-granular schedule: a held block entirely in this
            # rank's future is fully masked — skip its einsums instead of
            # computing then discarding them (halves total causal FLOPs;
            # NOTE the contiguous layout still concentrates the remaining
            # work on high ranks — zigzag_attention.py is the balanced
            # variant that also cuts the critical path).
            mode = causal_block_mode(src, my)
            acc, m, l = switched_block_update(
                q, k_cur, v_cur, (acc, m, l), mode, scale
            )
        else:
            acc, m, l = _block_update(q, k_cur, v_cur, acc, m, l, 0, 0,
                                      causal=False, scale=scale)
        return (k_nxt, v_nxt, acc, m, l), None

    (_, _, acc, _, l), _ = jax.lax.scan(body, (k, v, acc0, m0, l0), jnp.arange(w))
    return (acc / l).astype(q.dtype)


def ring_self_attention(
    q, k, v, mesh: Mesh, causal: bool = False,
    dp_axis: str | None = "dp", sp_axis: str = "sp", tp_axis: str | None = None,
):
    """Full-array entry point: q/k/v are (batch, seq, heads, head_dim) global
    arrays with batch sharded over `dp_axis`, sequence over `sp_axis`, and
    (optionally) heads over `tp_axis`; wraps `ring_attention` in shard_map."""
    spec = P(dp_axis, sp_axis, tp_axis, None)
    fn = shard_map(
        partial(ring_attention, axis_name=sp_axis, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
    )
    return fn(q, k, v)
