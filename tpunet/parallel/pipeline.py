"""Pipeline parallelism — GPipe microbatch schedule over a `pp` mesh axis.

SPMD formulation (the TPU-idiomatic one — no per-stage programs, one jitted
program on every device): stage s holds the parameters of its layer slice
(stacked leading dim sharded over `pp`); a scan runs M + W - 1 ticks, every
device applies its stage to one microbatch per tick, and activations hop to
the next stage via `lax.ppermute`. Bubbles at fill/drain compute on dummy
data and are masked out of the result. Autodiff flows through scan+ppermute,
so the same schedule serves forward and backward (the backward pipeline runs
in reverse automatically).

Constraint: every stage must map (microbatch, ...) -> same shape/dtype (true
for stacks of identical transformer blocks). Peak activation memory per
device is O(one microbatch), the point of pipelining.

The reference repo has no pipeline parallelism (SURVEY §2.3 "absent" — it
is transport only); this module is part of the parallelism capability the
TPU build adds above the transport layer.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpunet.parallel.smap import full_varying, shard_map, vma_of


def stack_stage_params(param_trees):
    """Stack per-stage param pytrees along a new leading dim (the `pp` axis).
    Use with per-stage inits: `stack_stage_params([init(s) for s in range(W)])`."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *param_trees)


def gpipe_stage_loop(stage_fn, stage_params, xs, axis_name: str):
    """Per-device GPipe schedule; call inside shard_map.

    stage_fn: (params, x) -> y with y.shape == x.shape.
    stage_params: this stage's params, leaves with leading dim 1 (the local
      shard of the stacked stage dim) — squeezed here.
    xs: (M, mb, ...) microbatched input, replicated across the pp axis.
    Returns (M, mb, ...) outputs, replicated (psum-broadcast from the last
    stage).
    """
    w = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    params = jax.tree.map(lambda a: a[0], stage_params)
    m = xs.shape[0]

    # The carries become pp-varying through the stage params / axis_index —
    # and additionally inherit whatever axes xs varies over (e.g. a dp axis
    # when microbatch rows are data-sharded). Fresh literals can't seed that
    # type, so cast explicitly to the union.
    carry_vma = tuple(dict.fromkeys((axis_name,) + vma_of(xs)))
    out0 = full_varying(xs.shape, 0.0, xs.dtype, carry_vma)
    recv0 = full_varying(xs.shape[1:], 0.0, xs.dtype, carry_vma)
    perm = [(i, (i + 1) % w) for i in range(w)]

    def tick(carry, t):
        recv, outs = carry
        # Stage 0 injects microbatch t during the fill phase; other stages
        # (and drain ticks) consume what arrived on the ring.
        inj = xs[jnp.clip(t, 0, m - 1)]
        x_in = jnp.where(idx == 0, jnp.where(t < m, inj, recv), recv)
        y = stage_fn(params, x_in)
        recv_next = jax.lax.ppermute(y, axis_name, perm)
        # The last stage emits microbatch t-(w-1) at tick t.
        oi = t - (w - 1)
        write = (oi >= 0) & (idx == w - 1)
        upd = jax.lax.dynamic_update_slice_in_dim(
            outs, y[None], jnp.clip(oi, 0, m - 1), axis=0
        )
        outs = jnp.where(write, upd, outs)
        return (recv_next, outs), None

    (_, outs), _ = jax.lax.scan(tick, (recv0, out0), jnp.arange(m + w - 1))
    # Replicate the last stage's outputs to every device.
    return jax.lax.psum(jnp.where(idx == w - 1, outs, jnp.zeros_like(outs)), axis_name)


def gpipe(
    stage_fn,
    stacked_params,
    x,
    mesh: Mesh,
    num_microbatches: int,
    pp_axis: str = "pp",
    dp_axis: str | None = None,
    remat_stages: bool = False,
):
    """Full-array entry point. stacked_params: pytree with leading stage dim
    W == mesh.shape[pp_axis] (see `stack_stage_params`); x: (batch, ...);
    returns (batch, ...). With `dp_axis`, each microbatch's row dim is
    additionally sharded over that mesh axis (pipeline x data parallelism:
    params stay dp-replicated, so shard_map's autodiff inserts the dp
    gradient psum on the transpose automatically).

    remat_stages: checkpoint the stage function, so the backward pipeline
    recomputes each tick's internal activations from its input instead of
    saving them — the scan otherwise stashes every tick's residuals
    (M + W - 1 ticks of full stage internals), which defeats pipelining's
    memory point for training. With it, per-device residency is the tick
    INPUTS only (one microbatch each) plus one stage's recompute."""
    w = mesh.shape[pp_axis]
    batch = x.shape[0]
    if batch % num_microbatches:
        raise ValueError(f"batch {batch} not divisible by {num_microbatches} microbatches")
    for leaf in jax.tree.leaves(stacked_params):
        if leaf.shape[0] != w:
            raise ValueError(
                f"stacked param leading dim {leaf.shape[0]} != pp axis size {w}"
            )
    mb = batch // num_microbatches
    if dp_axis is not None and mb % mesh.shape[dp_axis]:
        raise ValueError(
            f"microbatch size {mb} not divisible by {dp_axis}={mesh.shape[dp_axis]}"
        )
    xs = x.reshape((num_microbatches, mb) + x.shape[1:])

    if remat_stages:
        stage_fn = jax.checkpoint(stage_fn)
    param_specs = jax.tree.map(lambda _: P(pp_axis), stacked_params)
    data_spec = P(None, dp_axis) if dp_axis is not None else P()
    fn = shard_map(
        partial(gpipe_stage_loop, stage_fn, axis_name=pp_axis),
        mesh=mesh,
        in_specs=(param_specs, data_spec),
        out_specs=data_spec,
    )
    ys = fn(stacked_params, xs)
    return ys.reshape((batch,) + ys.shape[2:])
