"""Small shared helpers for shard_map-based collectives code.

jax >= 0.9 tracks varying-manual-axes (vma) in avals inside shard_map:
fresh literals (zeros/full) are "unvarying" and cannot meet device-varying
values in a scan carry without an explicit cast. `full_varying_like` builds
a filled array that carries the vma of a reference value, portably across
jax versions (pcast / pvary / no-op).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

try:  # jax >= 0.4.35 re-exports shard_map at top level
    shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # noqa: F401


def vma_of(x) -> tuple:
    try:
        return tuple(jax.typeof(x).vma)
    except AttributeError:  # older jax: no vma tracking
        return ()


def full_varying(shape, fill, dtype, vma: tuple):
    x = jnp.full(shape, fill, dtype)
    if not vma:
        return x
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, vma, to="varying")
    return jax.lax.pvary(x, vma)
