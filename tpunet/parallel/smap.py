"""Small shared helpers for shard_map-based collectives code.

jax >= 0.9 tracks varying-manual-axes (vma) in avals inside shard_map:
fresh literals (zeros/full) are "unvarying" and cannot meet device-varying
values in a scan carry without an explicit cast. `full_varying_like` builds
a filled array that carries the vma of a reference value, portably across
jax versions (pcast / pvary / no-op).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

try:  # jax >= 0.4.35 re-exports shard_map at top level
    _shard_map = jax.shard_map
except AttributeError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map


def shard_map(*args, **kwargs):
    """shard_map with a portability shim: jax versions WITHOUT the
    pcast/pvary varying-cast ops cannot express "this literal is varying",
    so their replication checker flags cond/scan branches that mix fresh
    literals with device-varying carries (the exact pattern the ring /
    zigzag attention scans use). On those versions the static check is
    disabled (check_rep=False — purely a compile-time lint, no codegen
    change); versions that HAVE the cast ops keep the check and the
    explicitly-cast literals from full_varying()."""
    if (getattr(jax.lax, "pcast", None) is None
            and getattr(jax.lax, "pvary", None) is None):
        kwargs.setdefault("check_rep", False)
    return _shard_map(*args, **kwargs)


def vma_of(x) -> tuple:
    try:
        return tuple(jax.typeof(x).vma)
    except AttributeError:  # older jax: no vma tracking
        return ()


def full_varying(shape, fill, dtype, vma: tuple):
    x = jnp.full(shape, fill, dtype)
    if not vma:
        return x
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, vma, to="varying")
    pvary = getattr(jax.lax, "pvary", None)
    if pvary is not None:
        return pvary(x, vma)
    # Neither cast op exists (0.4.x line): vma may be reported on avals but
    # there is no explicit cast — fresh literals already meet varying values
    # without one on these versions.
    return x
