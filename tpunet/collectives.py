"""Python collectives over the tpunet ring communicator.

The role NCCL's algorithm layer played above the reference plugin, exposed
to Python/NumPy. All ranks must call the same collectives in the same order
(MPI semantics). Arrays must be C-contiguous; results come back as NumPy
arrays of the input dtype.

Supported dtypes: float32, float64, bfloat16 (via ml_dtypes), int32, int64,
uint8. Ops: sum, prod, min, max.
"""

from __future__ import annotations

import ctypes
import os
from typing import Any

import numpy as np

from tpunet import _native

try:  # bf16 is first-class on TPU; ml_dtypes ships with jax
    import ml_dtypes

    _BF16 = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    _BF16 = None

_OPS = {"sum": 0, "prod": 1, "min": 2, "max": 3}


def _dtype_code(dt: np.dtype) -> int:
    dt = np.dtype(dt)
    if dt == np.float32:
        return 0
    if dt == np.float64:
        return 1
    if _BF16 is not None and dt == _BF16:
        return 2
    if dt == np.int32:
        return 3
    if dt == np.int64:
        return 4
    if dt == np.uint8:
        return 5
    raise TypeError(f"unsupported dtype for tpunet collectives: {dt}")


def _c_contig(arr: np.ndarray) -> np.ndarray:
    return arr if arr.flags.c_contiguous else np.ascontiguousarray(arr)


class AsyncResult:
    """Handle for a nonblocking collective. Pins the send/recv buffers until
    `wait()` — the native layer reads/writes them from its worker thread."""

    def __init__(self, comm: "Communicator", ticket: int, send: np.ndarray,
                 out: np.ndarray):
        self._comm = comm
        self._ticket = ticket
        self._send = send  # keep alive until wait
        self._out: np.ndarray | None = out

    def test(self) -> bool:
        """True iff the collective has completed (non-blocking)."""
        if self._send is None:  # already waited: the native ticket is gone
            return True
        done = ctypes.c_uint8(0)
        _native.check(
            self._comm._lib.tpunet_comm_ticket_test(
                self._comm._id, self._ticket, ctypes.byref(done)
            ),
            "ticket_test",
        )
        return bool(done.value)

    def wait(self) -> np.ndarray:
        """Block until complete; returns the result array. Idempotent."""
        if self._send is not None:
            try:
                _native.check(
                    self._comm._lib.tpunet_comm_ticket_wait(self._comm._id, self._ticket),
                    "ticket_wait",
                )
            finally:
                # Error or not, a returned WaitTicket means the native job
                # reached completion (or was dropped unstarted) — the worker
                # thread no longer touches the buffers, so release the pins.
                self._send = None
        return self._out

    def __del__(self):
        # Dropping an un-waited result must NOT free the buffers while the
        # native worker thread may still be reducing into them (observed:
        # exit-time SIGSEGV when a peer died with queued tickets). Quiesce
        # first; after a comm error the remaining jobs fail fast, so this
        # wait is bounded. Raw call, no check: errors here are expected
        # (failed jobs, already-destroyed comm) and __del__ must not raise.
        send = getattr(self, "_send", None)
        if send is not None:
            try:
                self._comm._lib.tpunet_comm_ticket_wait(self._comm._id, self._ticket)
            except Exception:  # noqa: BLE001 — interpreter teardown
                pass
            self._send = None


class Communicator:
    """Ring communicator; rank/world/coordinator default from env
    (TPUNET_RANK/RANK, TPUNET_WORLD_SIZE/WORLD_SIZE, TPUNET_COORDINATOR).

    Failure model (docs/DESIGN.md): collectives raise typed subclasses of
    ``_native.NativeError`` — ``CorruptionError`` for a CRC32C-detected wire
    corruption (TPUNET_CRC=1; the comm survives), ``ProgressTimeoutError``
    when the progress watchdog (TPUNET_PROGRESS_TIMEOUT_MS) flags a
    live-but-stuck peer, and plain NativeError for disconnect/poison. A
    single data-stream loss is NOT an error: the transport fails over onto
    the surviving streams and the collective completes (see
    ``tpunet_stream_failovers_total`` in telemetry.metrics())."""

    def __init__(
        self,
        coordinator: str | None = None,
        rank: int | None = None,
        world_size: int | None = None,
        wire_dtype: str | None = None,
        algo: str | None = None,
        traffic_class: str | None = None,
    ):
        env = os.environ
        coordinator = coordinator or env.get("TPUNET_COORDINATOR", "127.0.0.1:29500")
        rank = rank if rank is not None else int(env.get("TPUNET_RANK", env.get("RANK", "0")))
        world_size = (
            world_size
            if world_size is not None
            else int(env.get("TPUNET_WORLD_SIZE", env.get("WORLD_SIZE", "1")))
        )
        self._lib = _native.load()
        cid = ctypes.c_size_t(0)
        # wire_dtype selects the f32 wire compression codec ("f32"/"bf16"/
        # "int8"; None defers to TPUNET_WIRE_DTYPE, default f32). algo pins
        # the collective schedule ("auto"/"ring"/"rhd"/"tree"; None defers
        # to TPUNET_ALGO, default auto — per-(collective, size, world)
        # selection through the built-in thresholds or the
        # TPUNET_DISPATCH_TABLE JSON from `busbw_sweep --emit-dispatch`).
        # traffic_class pins the QoS lane every comm this communicator
        # wires will carry ("latency"/"bulk"/"control"; None defers to
        # TPUNET_TRAFFIC_CLASS, default bulk — gradient comms unchanged).
        # All three are negotiated at wiring time: a cross-rank
        # disagreement raises CodecMismatchError (codec) / NativeError
        # (algo, dispatch table, traffic class) on every rank before any
        # payload could be mis-decoded, any half-world schedule could
        # deadlock, or half a group could ride another QoS lane.
        _native.check(
            self._lib.tpunet_comm_create_ex(
                coordinator.encode(), rank, world_size,
                (wire_dtype or "").encode(), (algo or "").encode(),
                (traffic_class or "").encode(),
                ctypes.byref(cid),
            ),
            "comm_create",
        )
        self._id = cid.value
        self.rank = rank
        self.world_size = world_size
        codec = ctypes.c_int32(0)
        _native.check(
            self._lib.tpunet_comm_wire_dtype(self._id, ctypes.byref(codec)),
            "comm_wire_dtype",
        )
        #: Negotiated wire codec name — authoritative (read back from the
        #: native layer, so env-default and explicit construction agree).
        self.wire_dtype: str = {0: "f32", 1: "bf16", 2: "int8"}[codec.value]

    # -- collectives -------------------------------------------------------

    def all_reduce(self, arr: Any, op: str = "sum", inplace: bool = False) -> np.ndarray:
        """AllReduce. inplace=True reduces into `arr` itself (must be a
        C-contiguous ndarray) — skips the send→recv staging copy, which
        matters at 100MB+ gradient-bucket sizes."""
        caller_arr = arr
        arr = np.asarray(arr)
        if inplace and (arr is not caller_arr or not arr.flags.c_contiguous):
            raise ValueError(
                "inplace=True requires a C-contiguous ndarray (a staging "
                "copy would leave the caller's buffer unchanged)"
            )
        arr = _c_contig(arr)
        out = arr if inplace else np.empty_like(arr)
        _native.check(
            self._lib.tpunet_comm_all_reduce(
                self._id,
                arr.ctypes.data if arr.size else None,
                out.ctypes.data if out.size else None,
                arr.size,
                _dtype_code(arr.dtype),
                _OPS[op],
            ),
            "all_reduce",
        )
        return out

    def iall_reduce(self, arr: Any, op: str = "sum") -> AsyncResult:
        """Nonblocking AllReduce: returns immediately with an AsyncResult;
        the reduction runs on the communicator's worker thread (submission
        order across ranks must match). `result.wait()` yields the reduced
        array — this is how a trainer overlaps gradient-bucket sync with
        backward compute."""
        arr = _c_contig(np.asarray(arr))
        out = np.empty_like(arr)
        ticket = ctypes.c_uint64(0)
        _native.check(
            self._lib.tpunet_comm_iall_reduce(
                self._id,
                arr.ctypes.data if arr.size else None,
                out.ctypes.data if out.size else None,
                arr.size,
                _dtype_code(arr.dtype),
                _OPS[op],
                ctypes.byref(ticket),
            ),
            "iall_reduce",
        )
        return AsyncResult(self, ticket.value, arr, out)

    def reduce_scatter(self, arr: Any, op: str = "sum") -> np.ndarray:
        """arr: leading axis divisible by world_size; returns this rank's
        reduced shard (shape[0] / world_size leading axis)."""
        arr = _c_contig(np.asarray(arr))
        if arr.shape[0] % self.world_size != 0:
            raise ValueError(
                f"leading axis {arr.shape[0]} not divisible by world size {self.world_size}"
            )
        out_shape = (arr.shape[0] // self.world_size,) + arr.shape[1:]
        out = np.empty(out_shape, dtype=arr.dtype)
        _native.check(
            self._lib.tpunet_comm_reduce_scatter(
                self._id,
                arr.ctypes.data if arr.size else None,
                out.ctypes.data if out.size else None,
                out.size,
                _dtype_code(arr.dtype),
                _OPS[op],
            ),
            "reduce_scatter",
        )
        return out

    def all_gather(self, arr: Any) -> np.ndarray:
        """Returns shape (world_size, *arr.shape), rank-ordered."""
        arr = _c_contig(np.asarray(arr))
        out = np.empty((self.world_size,) + arr.shape, dtype=arr.dtype)
        _native.check(
            self._lib.tpunet_comm_all_gather(
                self._id,
                arr.ctypes.data if arr.size else None,
                out.ctypes.data if out.size else None,
                arr.nbytes,
            ),
            "all_gather",
        )
        return out

    def broadcast(self, arr: Any, root: int = 0) -> np.ndarray:
        arr = np.ascontiguousarray(np.asarray(arr)).copy()
        _native.check(
            self._lib.tpunet_comm_broadcast(
                self._id, arr.ctypes.data if arr.size else None, arr.nbytes, root
            ),
            "broadcast",
        )
        return arr

    def all_to_all(self, arr: Any) -> np.ndarray:
        """arr: leading axis == world_size, block j destined for rank j.
        Returns the same shape with block j originating at rank j — the
        Ulysses sequence-parallel / cross-host MoE dispatch primitive."""
        arr = _c_contig(np.asarray(arr))
        if arr.shape[0] != self.world_size:
            raise ValueError(
                f"leading axis {arr.shape[0]} must equal world size {self.world_size}"
            )
        out = np.empty_like(arr)
        _native.check(
            self._lib.tpunet_comm_all_to_all(
                self._id,
                arr.ctypes.data if arr.size else None,
                out.ctypes.data if out.size else None,
                arr.nbytes // self.world_size,
            ),
            "all_to_all",
        )
        return out

    def all_to_all_typed(self, arr: Any) -> np.ndarray:
        """Typed AllToAll: like :meth:`all_to_all`, but blocks are ELEMENTS
        of the array's dtype, and float32 blocks honor the communicator's
        negotiated wire codec (``wire_dtype="bf16"``/``"int8"``) — every
        non-self block is encoded once at the source (int8 scale blocks
        restart per (src, dst) block) and decoded once at the destination,
        so results are bit-identical across the pairwise / relay /
        hierarchical routes and each block's error stays inside the
        documented |err| <= amax/254 bound. The MoE dispatch/combine
        primitive (tpunet.workloads.moe)."""
        arr = _c_contig(np.asarray(arr))
        if arr.shape[0] != self.world_size:
            raise ValueError(
                f"leading axis {arr.shape[0]} must equal world size {self.world_size}"
            )
        out = np.empty_like(arr)
        _native.check(
            self._lib.tpunet_comm_all_to_all_typed(
                self._id,
                arr.ctypes.data if arr.size else None,
                out.ctypes.data if out.size else None,
                arr.size // self.world_size,
                _dtype_code(arr.dtype),
            ),
            "all_to_all_typed",
        )
        return out

    def iall_to_all(self, arr: Any) -> AsyncResult:
        """Nonblocking AllToAll (byte-oriented): returns immediately with an
        AsyncResult; mesh-routed schedules run on the communicator's
        dedicated mesh worker, so an async AllToAll overlaps async ring
        AllReduces on disjoint comms instead of queueing behind them.
        Submission order across ranks must match, like iall_reduce."""
        arr = _c_contig(np.asarray(arr))
        if arr.shape[0] != self.world_size:
            raise ValueError(
                f"leading axis {arr.shape[0]} must equal world size {self.world_size}"
            )
        out = np.empty_like(arr)
        ticket = ctypes.c_uint64(0)
        _native.check(
            self._lib.tpunet_comm_iall_to_all(
                self._id,
                arr.ctypes.data if arr.size else None,
                out.ctypes.data if out.size else None,
                arr.nbytes // self.world_size,
                ctypes.byref(ticket),
            ),
            "iall_to_all",
        )
        return AsyncResult(self, ticket.value, arr, out)

    def neighbor_exchange(self, arr: Any) -> np.ndarray:
        """Send arr to (rank+1)%W, receive the same-shaped message from
        (rank-1+W)%W — the ring-attention / sequence-parallel shift step."""
        arr = _c_contig(np.asarray(arr))
        out = np.empty_like(arr)
        got = ctypes.c_uint64(0)
        _native.check(
            self._lib.tpunet_comm_neighbor_exchange(
                self._id,
                arr.ctypes.data if arr.size else None,
                arr.nbytes,
                out.ctypes.data if out.size else None,
                out.nbytes,
                ctypes.byref(got),
            ),
            "neighbor_exchange",
        )
        if got.value != arr.nbytes:
            raise RuntimeError(
                f"neighbor_exchange size mismatch: sent {arr.nbytes}, got {got.value}"
            )
        return out

    def barrier(self) -> None:
        _native.check(self._lib.tpunet_comm_barrier(self._id), "barrier")

    def set_as_default(self) -> None:
        """Make this the process-default communicator — the handle the XLA
        FFI custom-call collectives (tpunet.interop) resolve at CALL time,
        so elastic recovery can swap the communicator under
        already-compiled executables (comm_destroy clears it)."""
        _native.check(
            self._lib.tpunet_comm_set_default(self._id), "comm_set_default")

    def close(self) -> None:
        if self._id:
            cid = ctypes.c_size_t(self._id)
            self._id = 0
            _native.check(self._lib.tpunet_comm_destroy(ctypes.byref(cid)), "comm_destroy")

    def __enter__(self) -> "Communicator":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()
