"""Elastic recovery: rebuild the communicator around a replacement rank.

The reference has no recovery story at all — a dead peer panics the job
(SURVEY §5 "Failure detection — essentially absent": 108 unwrap sites, no
retry/reconnect). tpunet already turns peer death into typed errors on every
rank (tests/test_fault_paths.py); this module adds the missing half:
survivors and a respawned replacement agree on a new *generation*, re-run
rendezvous on a generation-derived coordinator port, and resume training
from the latest checkpoint.

Protocol (no side channel beyond the shared checkpoint/rendezvous dir that
an elastic deployment already has):

1. Generation g trains on coordinator ``host:(port+g)``.
2. A rank dies. Every survivor's next collective raises a typed comm error
   (the transport's keepalive/poisoning guarantees this — no hangs).
3. Survivors: ``finalize()``, bump g, publish it to ``<dir>/GENERATION``
   (atomic rename; last writer wins with the same value), rebuild at the new
   port. The bootstrap blocks until all ``world_size`` ranks arrive.
4. The replacement process (respawned by the job scheduler / supervisor)
   reads ``GENERATION`` and joins. If it raced ahead of the survivors'
   bump it fails rendezvous after TPUNET_BOOTSTRAP_TIMEOUT_MS, re-reads,
   and retries — convergence needs no ordering between respawn and bump.
5. Everyone restores the latest checkpoint and continues. Exact-resume is
   the checkpoint layer's contract (tests/test_checkpoint.py), so a crashed
   step is replayed, not lost.

The train callback owns the step loop so it can checkpoint at its own
cadence; ``run_elastic`` owns failure classification and the rebuild loop.
"""

from __future__ import annotations

import os
import time
from pathlib import Path
from typing import Any, Callable

from tpunet import distributed
from tpunet._native import NativeError
from tpunet.collectives import Communicator

GENERATION_FILE = "GENERATION"


def read_generation(directory: str | Path) -> int:
    """Current generation published in `directory` (0 if never written)."""
    try:
        return int((Path(directory) / GENERATION_FILE).read_text().strip())
    except (FileNotFoundError, ValueError):
        return 0


def write_generation(directory: str | Path, generation: int) -> None:
    """Atomically publish `generation` (rename; concurrent writers of the
    same value — every survivor — are idempotent)."""
    path = Path(directory) / GENERATION_FILE
    tmp = path.with_name(f".{GENERATION_FILE}.{os.getpid()}.tmp")
    tmp.write_text(f"{generation}\n")
    os.replace(tmp, path)


def is_comm_failure(exc: BaseException) -> bool:
    """True when `exc` means the communicator (not the training math) broke:
    a NativeError from the transport/collectives, or a wrapper carrying one
    in its message or EXPLICIT cause chain (XlaRuntimeError from the
    io_callback path stringifies the original NativeError; ``raise X from
    err`` sets __cause__). Implicit __context__ is deliberately NOT walked:
    an unrelated error raised while handling a comm error (say, a NaN-loss
    ValueError inside an except block) must still propagate, not be
    "recovered" into silent restarts."""
    seen: set[int] = set()
    cur: BaseException | None = exc
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        if isinstance(cur, NativeError):
            return True
        if "tpunet native" in str(cur):
            return True
        cur = cur.__cause__
    return False


def generation_coordinator(coordinator: str, generation: int) -> str:
    host, port = coordinator.rsplit(":", 1)
    return f"{host}:{int(port) + generation}"


def run_elastic(
    train_once: Callable[[Communicator, int], Any],
    *,
    coordinator: str,
    rank: int,
    world_size: int,
    directory: str | Path,
    max_restarts: int = 2,
    generation: int | None = None,
    rejoin_delay_s: float = 0.5,
    join_timeout_s: float = 600.0,
) -> Any:
    """Run ``train_once(comm, generation)`` under elastic recovery.

    Returns train_once's return value. Comm failures during TRAINING trigger
    rebuild (up to ``max_restarts`` across the job's life in this process);
    any other exception propagates immediately — a loss blowup must not be
    "recovered" into silent data loss.

    Rendezvous failures spend wall-clock, not restarts: the process re-reads
    the published generation and retries until ``join_timeout_s`` elapses
    without a successful join. Only processes that HELD a live communicator
    bump and publish the generation (monotonically); a joiner that cannot
    rendezvous never publishes — a replacement racing ahead of the
    survivors' bump would otherwise publish generations nobody listens on
    and strand the job.

    ``generation=None`` starts from the published generation — what a
    respawned replacement wants; survivors carry their generation forward
    in-process.
    """
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    g = read_generation(directory) if generation is None else generation
    restarts = 0
    join_deadline = time.monotonic() + join_timeout_s

    while True:
        comm = None
        try:
            distributed.finalize()  # no-op unless a previous comm is live
            comm = distributed.initialize(
                generation_coordinator(coordinator, g), rank, world_size
            )
            join_deadline = time.monotonic() + join_timeout_s
            return train_once(comm, g)
        except Exception as exc:  # noqa: BLE001 — classified below
            if not is_comm_failure(exc):
                raise
            distributed.finalize()
            if comm is None:
                # Rendezvous failed — likely a stale generation (this is the
                # replacement racing the survivors' bump, or the survivors
                # already moved again). Adopt the published value and retry;
                # never publish, never burn a restart.
                if time.monotonic() > join_deadline:
                    raise
                published = read_generation(directory)
                g = max(g, published)
            else:
                restarts += 1
                if restarts > max_restarts:
                    raise
                # Sole publishers are ranks that lost a LIVE communicator;
                # they agree on the increment, and max() keeps the published
                # value monotonic even across overlapping failures.
                g = max(g + 1, read_generation(directory))
                write_generation(directory, g)
                # A fresh rebuild opens a fresh join window — without this, a
                # failure arriving join_timeout_s after the last successful
                # join would start the rendezvous retries already expired.
                join_deadline = time.monotonic() + join_timeout_s
            time.sleep(rejoin_delay_s)
