"""Elastic recovery: rebuild the communicator around a replacement rank.

The reference has no recovery story at all — a dead peer panics the job
(SURVEY §5 "Failure detection — essentially absent": 108 unwrap sites, no
retry/reconnect). tpunet already turns peer death into typed errors on every
rank (tests/test_fault_paths.py); this module adds the missing half:
survivors and a respawned replacement agree on a new *generation*, re-run
rendezvous on a generation-derived coordinator port, and resume training
from the latest checkpoint.

Protocol (no side channel beyond the shared checkpoint/rendezvous dir that
an elastic deployment already has):

1. Generation g trains on coordinator ``host:(port+g)``.
2. A rank dies. Every survivor's next collective raises a typed comm error
   (the transport's keepalive/poisoning guarantees this — no hangs).
3. Survivors: ``finalize()``, bump g, publish it to ``<dir>/GENERATION``
   (atomic rename; last writer wins with the same value), rebuild at the new
   port. The bootstrap blocks until all ``world_size`` ranks arrive.
4. The replacement process (respawned by the job scheduler / supervisor)
   reads ``GENERATION`` and joins. If it raced ahead of the survivors'
   bump it fails rendezvous after TPUNET_BOOTSTRAP_TIMEOUT_MS, re-reads,
   and retries — convergence needs no ordering between respawn and bump.
5. Everyone restores the latest checkpoint and continues. Exact-resume is
   the checkpoint layer's contract (tests/test_checkpoint.py), so a crashed
   step is replayed, not lost.

With ``allow_shrink=True`` steps 3-4 change policy: instead of waiting for
a replacement, survivors seal a smaller membership after a grace window and
continue at world-1 with re-assigned ranks (see _shrink_rendezvous).

The train callback owns the step loop so it can checkpoint at its own
cadence; ``run_elastic`` owns failure classification and the rebuild loop.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Any, Callable

from tpunet import distributed
from tpunet._native import NativeError
from tpunet.collectives import Communicator

GENERATION_FILE = "GENERATION"


def read_generation(directory: str | Path) -> int:
    """Current generation published in `directory` (0 if never written)."""
    try:
        return int((Path(directory) / GENERATION_FILE).read_text().strip())
    except (FileNotFoundError, ValueError):
        return 0


def write_generation(directory: str | Path, generation: int) -> None:
    """Atomically publish `generation` (rename; concurrent writers of the
    same value — every survivor — are idempotent)."""
    path = Path(directory) / GENERATION_FILE
    tmp = path.with_name(f".{GENERATION_FILE}.{os.getpid()}.tmp")
    tmp.write_text(f"{generation}\n")
    os.replace(tmp, path)


def is_comm_failure(exc: BaseException) -> bool:
    """True when `exc` means the communicator (not the training math) broke:
    a NativeError from the transport/collectives, or a wrapper carrying one
    in its message or EXPLICIT cause chain (XlaRuntimeError from the
    io_callback path stringifies the original NativeError; ``raise X from
    err`` sets __cause__). Implicit __context__ is deliberately NOT walked:
    an unrelated error raised while handling a comm error (say, a NaN-loss
    ValueError inside an except block) must still propagate, not be
    "recovered" into silent restarts.

    The typed failure-model errors are NativeError subclasses and classify
    accordingly: a ProgressTimeoutError (TPUNET_PROGRESS_TIMEOUT_MS — peer
    alive but stuck) triggers the SAME generation rebuild as a dead peer,
    and a CorruptionError (CRC32C mismatch under TPUNET_CRC=1) rebuilds
    rather than silently reducing damaged gradients."""
    seen: set[int] = set()
    cur: BaseException | None = exc
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        if isinstance(cur, NativeError):
            return True
        if "tpunet native" in str(cur):
            return True
        cur = cur.__cause__
    return False


def generation_coordinator(coordinator: str, generation: int) -> str:
    host, port = coordinator.rsplit(":", 1)
    return f"{host}:{int(port) + generation}"


class ExcludedFromMembership(RuntimeError):
    """This process missed a shrink's grace window (or joined after the
    membership doc was sealed) and is no longer part of the job."""


def membership_rendezvous(directory: Path, generation: int, member_id: int,
                          advertise_host: str, base_port: int,
                          grace_s: float) -> tuple[str, int, int, list[int]]:
    """Agree on `generation`'s membership and return
    (coordinator, new_rank, new_world, members).

    Every participant — survivor OR joiner; the protocol cannot tell them
    apart, which is exactly what makes the same window serve both shrink
    and grow (tpunet.elastic.ElasticWorld) — writes a member file naming
    its advertise host, then the LEADER — lowest member id present after
    the grace window — seals ``MEMBERS.json`` exactly once (O_EXCL: a late
    lower id that lost the race adopts the sealed doc rather than
    rewriting membership under peers already rendezvousing). Member ids
    are the caller's stable ids, not per-generation ranks; new ranks are
    the sealed members' sort order. Participants absent from the sealed
    doc raise ExcludedFromMembership — the grace window IS the membership
    contract.
    """
    gdir = directory / f"g{generation}"
    gdir.mkdir(parents=True, exist_ok=True)
    # Atomic publish (tmp + replace): the sealing leader reads these files
    # the moment they appear in its glob, and a torn/empty advertise host
    # would be sealed into an immutable doc as a broken coordinator. The
    # dot-prefixed tmp never matches the member_* glob.
    tmp = gdir / f".member_{member_id}.{os.getpid()}.tmp"
    tmp.write_text(advertise_host)
    os.replace(tmp, gdir / f"member_{member_id}")
    doc_path = gdir / "MEMBERS.json"

    def members_present() -> list[int]:
        return sorted(int(p.name.split("_", 1)[1]) for p in gdir.glob("member_*"))

    deadline = time.monotonic() + grace_s
    while not doc_path.exists():
        present = members_present()
        if present and present[0] == member_id and time.monotonic() >= deadline:
            # Leader after a full grace window: seal what arrived.
            sealed = {
                "members": present,
                "hosts": {str(m): (gdir / f"member_{m}").read_text()
                          for m in present},
            }
            tmp = gdir / f".members.{os.getpid()}.tmp"
            tmp.write_text(json.dumps(sealed))
            try:
                # Atomic exclusive publish of a COMPLETE file: link() fails
                # with EEXIST if another leader sealed first (no TOCTOU, no
                # torn reads) — the loser adopts the sealed doc below.
                os.link(tmp, doc_path)
            except FileExistsError:
                pass
            finally:
                tmp.unlink(missing_ok=True)
            break
        if time.monotonic() > deadline + 4 * grace_s:
            raise RuntimeError(
                f"shrink membership for generation {generation} never sealed "
                f"(leader {present[0] if present else '?'} missing?)"
            )
        time.sleep(0.1)

    doc = json.loads(doc_path.read_text())
    members: list[int] = doc["members"]
    if member_id not in members:
        raise ExcludedFromMembership(
            f"member {member_id} missed generation {generation}'s grace window "
            f"(sealed members: {members})"
        )
    new_rank = members.index(member_id)
    coordinator = f"{doc['hosts'][str(members[0])]}:{base_port + generation}"
    return coordinator, new_rank, len(members), members


def _shrink_rendezvous(directory: Path, generation: int, member_id: int,
                       advertise_host: str, base_port: int,
                       grace_s: float) -> tuple[str, int, int]:
    """run_elastic's 3-tuple view of membership_rendezvous (shrink policy
    never needs the member list)."""
    coordinator, new_rank, new_world, _ = membership_rendezvous(
        directory, generation, member_id, advertise_host, base_port, grace_s)
    return coordinator, new_rank, new_world


def run_elastic(
    train_once: Callable[[Communicator, int], Any],
    *,
    coordinator: str,
    rank: int,
    world_size: int,
    directory: str | Path,
    max_restarts: int = 2,
    generation: int | None = None,
    rejoin_delay_s: float = 0.5,
    join_timeout_s: float = 600.0,
    allow_shrink: bool = False,
    shrink_grace_s: float = 10.0,
    min_world: int = 1,
    advertise_host: str | None = None,
) -> Any:
    """Run ``train_once(comm, generation)`` under elastic recovery.

    Returns train_once's return value. Comm failures during TRAINING trigger
    rebuild (up to ``max_restarts`` across the job's life in this process);
    any other exception propagates immediately — a loss blowup must not be
    "recovered" into silent data loss.

    Rendezvous failures spend wall-clock, not restarts: the process re-reads
    the published generation and retries until ``join_timeout_s`` elapses
    without a successful join. Only processes that HELD a live communicator
    bump and publish the generation (monotonically); a joiner that cannot
    rendezvous never publishes — a replacement racing ahead of the
    survivors' bump would otherwise publish generations nobody listens on
    and strand the job.

    ``generation=None`` starts from the published generation — what a
    respawned replacement wants; survivors carry their generation forward
    in-process.

    ``allow_shrink=True`` switches recovery policy from
    wait-for-a-replacement to CONTINUE WITHOUT THE DEAD RANK: survivors run
    a grace-window membership rendezvous through the shared directory (see
    _shrink_rendezvous) and rebuild with re-assigned ranks, a smaller world,
    and a coordinator re-elected onto the lowest surviving member's
    ``advertise_host`` (so losing rank 0's host is survivable — which is why
    multi-host callers MUST pass their own reachable address; only loopback
    setups may omit it). ``rank`` doubles as the stable member id.
    ``train_once`` must read its rank/world from the comm, not the closure.
    Shrinking below ``min_world`` raises instead of limping on.
    """
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    g = read_generation(directory) if generation is None else generation
    member_id = rank
    cur_coordinator = generation_coordinator(coordinator, g)
    cur_rank, cur_world = rank, world_size
    base_host, base_port = coordinator.rsplit(":", 1)
    if allow_shrink and advertise_host is None:
        # No safe multi-host default exists: advertising the ORIGINAL
        # coordinator's host would re-elect the new coordinator onto the
        # very machine whose death we are shrinking around. Loopback dev
        # setups are unambiguous; everyone else must say who they are.
        if base_host in ("127.0.0.1", "localhost", "::1"):
            advertise_host = base_host
        else:
            raise ValueError(
                "allow_shrink=True on a non-loopback coordinator requires "
                "advertise_host=<this machine's reachable address> — the "
                "re-elected coordinator binds on a surviving member's host"
            )
    restarts = 0
    ever_joined = False
    join_deadline = time.monotonic() + join_timeout_s

    while True:
        comm = None
        try:
            distributed.finalize()  # no-op unless a previous comm is live
            comm = distributed.initialize(cur_coordinator, cur_rank, cur_world)
            ever_joined = True
            join_deadline = time.monotonic() + join_timeout_s
            return train_once(comm, g)
        except Exception as exc:  # noqa: BLE001 — classified below
            if not is_comm_failure(exc):
                raise
            distributed.finalize()
            if comm is None:
                # Rendezvous failed. Never burn a restart here; bound by
                # wall-clock instead.
                if time.monotonic() > join_deadline:
                    raise
                g = max(g, read_generation(directory))
                if not allow_shrink:
                    # Replacement policy: adopt the published generation and
                    # retry — the survivors' bump is what we're chasing.
                    cur_coordinator = generation_coordinator(coordinator, g)
                elif ever_joined:
                    # Shrink policy, and this process WAS part of a running
                    # job: a sealed generation that cannot assemble means a
                    # member died between seal and rebuild. There is no
                    # replacement to wait for — advance and re-run
                    # membership without it. (Before the first successful
                    # join, fall through and just retry: sealing at startup
                    # could permanently exclude a healthy-but-slow rank.)
                    g = max(g + 1, read_generation(directory))
                    write_generation(directory, g)
                    cur_coordinator, cur_rank, cur_world = _shrink_rendezvous(
                        directory, g, member_id, advertise_host,
                        int(base_port), shrink_grace_s,
                    )
                    if cur_world < min_world:
                        raise RuntimeError(
                            f"membership shrank to {cur_world} < min_world "
                            f"{min_world}"
                        )
            else:
                restarts += 1
                if restarts > max_restarts:
                    raise
                # Sole publishers are ranks that lost a LIVE communicator;
                # they agree on the increment, and max() keeps the published
                # value monotonic even across overlapping failures.
                g = max(g + 1, read_generation(directory))
                write_generation(directory, g)
                if allow_shrink:
                    cur_coordinator, cur_rank, cur_world = _shrink_rendezvous(
                        directory, g, member_id, advertise_host,
                        int(base_port), shrink_grace_s,
                    )
                    if cur_world < min_world:
                        raise RuntimeError(
                            f"membership shrank to {cur_world} < min_world "
                            f"{min_world}"
                        )
                else:
                    cur_coordinator = generation_coordinator(coordinator, g)
                # A fresh rebuild opens a fresh join window — without this, a
                # failure arriving join_timeout_s after the last successful
                # join would start the rendezvous retries already expired.
                join_deadline = time.monotonic() + join_timeout_s
            time.sleep(rejoin_delay_s)
