"""High-level training driver: data -> step -> checkpoint -> resume.

`fit()` is the convenience loop tying the framework's pieces together the
way the benchmarks do by hand: a (possibly prefetched) batch iterator, the
jitted train step from `make_train_step`, periodic orbax checkpoints with
exact resume, and a metrics hook. It stays deliberately thin — every
capability (DCN tier, ZeRO, accumulation, fused xent) is configured on the
step function itself, so fit() composes with all of them instead of
re-exposing their knobs.

The reference has no trainer at all (it is a transport; its end-to-end
validation drove an external synthetic benchmark — reference
README.md:52-84). This is framework capability above it.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterable

import jax

from tpunet.train.checkpoint import CheckpointManager
from tpunet.train.trainer import TrainState


def fit(
    state: TrainState,
    train_step: Callable,
    batches: Iterable,
    *,
    steps: int,
    rng=None,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 0,
    max_to_keep: int = 3,
    log_every: int = 0,
    log_fn: Callable[[dict[str, Any]], None] | None = None,
    eval_every: int = 0,
    eval_fn: Callable[[TrainState], dict[str, Any]] | None = None,
    skip_batches_on_resume: bool = False,
    prefetch: int = 0,
    prefetch_sharding=None,
) -> TrainState:
    """Run `steps` optimizer steps (counted by state.step, so a resumed run
    finishes the SAME total schedule, not `steps` more).

    state: from create_train_state (resume is handled here when
        checkpoint_dir holds a checkpoint — the freshly-initialized state
        supplies structure and shardings for the restore).
    train_step: make_train_step(...)-style (state, inputs, labels, rng) ->
        (state, loss).
    batches: yields (inputs, labels); pass `prefetch=2` to overlap
        host->HBM transfer (fit wraps the stream itself, after any resume
        skip).
    rng: PRNGKey folded with the step counter for per-step dropout keys.
    checkpoint_every: save every k steps (and once at the end) when
        checkpoint_dir is set; 0 = only the final save.
    log_fn: called with {"step", "loss", "steps_per_s"} every `log_every`
        steps (default print), AND — when eval_fn is set — with
        {"step", "eval": {...}} records at eval points: log_fn
        implementations must dispatch on the presence of the "eval" key.
        Loss is fetched to host ONLY at log/final steps — fetching every
        step would serialize dispatch (and on the tunneled TPU platform
        per-step sync is wrong anyway, PERF_NOTES).
    eval_fn: called with the CURRENT state every `eval_every` steps (and
        once after the final step); its returned metrics dict is passed to
        log_fn with the step under {"step", "eval": {...}}. Run your eval
        set inside it with a jitted eval step — fit() stays agnostic to
        what "evaluation" means. eval_every=0 with an eval_fn set means
        final-step evaluation only.
    skip_batches_on_resume: when resuming at step k, first discard k
        batches from the iterator, so a deterministic stream (e.g.
        token_batches with a fixed seed) lines up exactly where the
        interrupted run left off and the resumed trajectory matches an
        uninterrupted one. Leave False for stateful/streaming sources that
        manage their own position.
    prefetch: when > 0, wrap the batch stream in
        tpunet.data.prefetch_to_device(size=prefetch) — HERE, after the
        resume skip, so skipped batches are a cheap host-side index
        advance, never materialized or transferred. Prefer this over
        wrapping `batches` yourself when also using
        skip_batches_on_resume. prefetch_sharding is passed through
        (e.g. batch_sharding(mesh)).
    """
    if rng is None:
        rng = jax.random.PRNGKey(0)
    mgr = (
        CheckpointManager(checkpoint_dir, max_to_keep=max_to_keep)
        if checkpoint_dir
        else None
    )
    try:
        if mgr is not None:
            restored = mgr.restore_latest(state)
            # Adopt the checkpoint only when it is AHEAD of the caller's
            # state: a caller that already restored a newer state from
            # elsewhere (e.g. elastic recovery choosing the most advanced
            # member checkpoint) must not be silently rolled back by an
            # older local checkpoint. Explicit rollback = restore manually.
            if restored is not None and int(restored.step) > int(state.step):
                state = restored

        def _default_log(m):
            if "eval" in m:
                print(f"[fit] step {m['step']} eval {m['eval']}", flush=True)
            else:
                print(f"[fit] step {m['step']} loss {m['loss']:.4f} "
                      f"({m['steps_per_s']:.2f} steps/s)", flush=True)

        log = log_fn or _default_log
        it = iter(batches)
        loss = None
        t0 = time.perf_counter()
        # Host-side mirror of state.step: reading the device scalar every
        # iteration (int(state.step)) would sync per step and serialize
        # dispatch — fetched ONCE here (post-restore), then incremented
        # locally in lockstep with the step function's step+1.
        done = int(state.step)
        start_step = done
        window_start = done
        last_eval_step = -1
        if skip_batches_on_resume and done:
            for _ in range(done):
                next(it, None)
        if prefetch > 0:
            from tpunet.data import prefetch_to_device

            it = prefetch_to_device(it, size=prefetch,
                                    sharding=prefetch_sharding)
        while done < steps:
            try:
                inputs, labels = next(it)
            except StopIteration:
                break  # finite dataset exhausted before the schedule
            step_rng = jax.random.fold_in(rng, done)
            state, loss = train_step(state, inputs, labels, step_rng)
            done += 1
            if log_every and done % log_every == 0:
                dt = time.perf_counter() - t0
                log({
                    "step": done,
                    "loss": float(loss),  # host transfer = the sync point
                    "steps_per_s": (done - window_start) / dt if dt > 0 else 0.0,
                })
                t0 = time.perf_counter()
                window_start = done
            if (eval_fn is not None and eval_every
                    and done % eval_every == 0 and done < steps):
                log({"step": done, "eval": eval_fn(state)})
                last_eval_step = done
                # Eval wall time must not deflate the NEXT window's
                # steps_per_s: restart the throughput window after it.
                t0 = time.perf_counter()
                window_start = done
            if mgr is not None and checkpoint_every and done % checkpoint_every == 0:
                mgr.save(done, state)
        if eval_fn is not None and done > start_step and done != last_eval_step:
            # Final evaluation on the finished state (also covers runs whose
            # stream ended early) — skipped for pure no-op re-invocations and
            # when the cadence already evaluated this exact step (a stream
            # exhausted right at an eval point must not eval twice).
            log({"step": done, "eval": eval_fn(state)})
        if mgr is not None:
            if done == start_step and start_step < steps:
                # The schedule wanted more steps but the stream yielded
                # none: still leave an artifact — a silent no-op run with a
                # configured checkpoint_dir would otherwise be undetectable.
                # (A re-invoked COMPLETED run — start_step >= steps — is a
                # legitimate no-op, not this case.)
                import warnings

                warnings.warn(
                    f"fit() ran 0 steps (state.step={done}, steps={steps}): "
                    "the batch stream was empty; ensuring a checkpoint "
                    "exists for the current state",
                    stacklevel=2,
                )
            # Skip when the cadence already saved this exact step: orbax's
            # force=True bypasses the save-interval policy but still raises
            # StepAlreadyExistsError on a duplicate step.
            if mgr.latest_step() != done:
                mgr.save(done, state, force=True)
            mgr.wait_until_finished()
    finally:
        if mgr is not None:
            mgr.close()
    return state
