"""Checkpoint / resume for training state (orbax-backed).

The reference transport is stateless and has no checkpointing (SURVEY §5
"Checkpoint/resume — absent"); the trainer tier of this framework needs it,
so this module provides the standard TPU-native shape: orbax
CheckpointManager with retention, async-safe save of the full TrainState
pytree (params + optimizer state + step), and sharding-aware restore — on a
multi-host mesh orbax writes one shard per host and restore honors the
target shardings, so checkpoints scale with the pod instead of gathering to
one host.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import orbax.checkpoint as ocp

from tpunet.train.trainer import TrainState


class CheckpointManager:
    """Thin wrapper over orbax for TrainState save/resume.

    Usage:
        mgr = CheckpointManager(dir, max_to_keep=3)
        mgr.save(int(state.step), state)           # during training
        state = mgr.restore_latest(state) or state # at startup (state = the
                                                   # freshly-initialized tree,
                                                   # provides structure+sharding)
    """

    def __init__(self, directory: str | Path, max_to_keep: int = 3):
        self._mgr = ocp.CheckpointManager(
            Path(directory).absolute(),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: TrainState, force: bool = False) -> bool:
        """Save (async under the hood; wait_until_finished() to block)."""
        return self._mgr.save(step, args=ocp.args.StandardSave(state._asdict()), force=force)

    def restore(self, step: int, target: TrainState) -> TrainState:
        """Restore a specific step. `target` supplies the tree structure and
        shardings (restored arrays land with the same placement)."""
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(target._asdict())
        )
        return TrainState(**restored)

    def restore_latest(self, target: TrainState) -> TrainState | None:
        """Resume from the newest checkpoint, or None if none exists."""
        step = self._mgr.latest_step()
        if step is None:
            return None
        return self.restore(step, target)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def all_steps(self) -> list[int]:
        return list(self._mgr.all_steps())

    def wait_until_finished(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()


def save_pytree(path: str | Path, tree: Any) -> None:
    """One-shot pytree save (no manager/retention)."""
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(Path(path).absolute(), tree)
    ckptr.wait_until_finished()
    ckptr.close()


def restore_pytree(path: str | Path, target: Any) -> Any:
    """One-shot restore; `target` supplies structure/shardings."""
    ckptr = ocp.StandardCheckpointer()
    try:
        return ckptr.restore(Path(path).absolute(), target)
    finally:
        ckptr.close()
