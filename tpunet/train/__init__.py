"""Training stack: jitted DP/TP train step + synthetic benchmark workload
(the reference's end-to-end validation was Bagua's VGG16
synthetic_benchmark.py, reference README.md:52)."""

from tpunet.train.checkpoint import (  # noqa: F401
    CheckpointManager,
    restore_pytree,
    save_pytree,
)
from tpunet.train.fit import fit  # noqa: F401
from tpunet.train.elastic import (  # noqa: F401
    ExcludedFromMembership,
    is_comm_failure,
    read_generation,
    run_elastic,
    write_generation,
)
from tpunet.train.trainer import (  # noqa: F401
    TrainState,
    create_train_state,
    create_zero_train_state,
    make_train_step,
    make_zero_train_step,
    synthetic_batch,
)
