"""Jitted training step with mesh shardings + optional cross-host gradient
sync over the tpunet DCN transport.

Design (TPU-first):
  * One jitted function contains forward, backward, and update — XLA fuses
    elementwise ops into the matmuls and inserts ICI collectives from the
    array shardings (batch over `dp`, Megatron-split classifier over `mdl`).
  * Cross-host gradient sync flattens the whole gradient pytree into ONE
    contiguous vector before the DCN all-reduce (`ravel_pytree`), so the
    multi-stream transport stripes a single large message instead of
    dribbling per-layer buffers — the same bucketing insight behind the
    reference's fairness design (large chunked messages saturate parallel
    streams; reference SURVEY §2.2 step 5).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.flatten_util import ravel_pytree


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray


def create_train_state(model, rng, sample_input, tx) -> tuple[TrainState, Any]:
    """Initialize params + optimizer state. Returns (state, apply_fn)."""
    params = model.init(rng, sample_input)["params"]
    opt_state = tx.init(params)
    return TrainState(params, opt_state, jnp.zeros((), jnp.int32)), model.apply


def make_train_step(model, tx, cross_host: bool = False, donate: bool = True,
                    grad_compression: str | None = None,
                    moe_aux_weight: float = 0.01):
    """Build the jitted train step.

    cross_host=True adds the DCN gradient all-reduce tier (requires
    tpunet.distributed.initialize() BEFORE the first trace — the decision
    is baked into the executable).

    grad_compression="bf16" casts the flattened gradient vector to bfloat16
    before the cross-host all-reduce and back after — halving DCN bytes for
    ~1 ulp of bf16 noise on already-noisy SGD gradients (the reference has
    no compression; its parent project's QAdam/bytegrad live a layer above —
    this is that capability at the transport-facing tier).

    When the model has MoE blocks (``n_experts > 0``), the Switch router's
    sown load-balancing losses are collected via mutable=['intermediates']
    and added to the loss scaled by ``moe_aux_weight`` — without this term
    the router can collapse onto one expert and capacity-drop most tokens.
    """
    if grad_compression not in (None, "bf16"):
        raise ValueError(f"unknown grad_compression {grad_compression!r}")
    has_moe = getattr(model, "n_experts", 0) > 0
    if cross_host:
        # Import here so single-host training never touches the transport.
        from tpunet import distributed
        from tpunet.interop import dcn_pmean

        distributed.world_size()  # raises early if initialize() was skipped

    def train_step(state: TrainState, images, labels, dropout_rng):
        def loss_fn(p):
            out = model.apply(
                {"params": p}, images, train=True, rngs={"dropout": dropout_rng},
                mutable=["intermediates"] if has_moe else False,
            )
            logits, mut = out if has_moe else (out, None)
            loss = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
            loss = loss.mean()
            if has_moe:
                # Each MoeMlp sows one scalar under .../moe_aux_loss; flax
                # wraps sown values in tuples, so sum all leaves on matching
                # paths and average over MoE blocks.
                aux = [
                    leaf
                    for path, leaf in jax.tree_util.tree_leaves_with_path(
                        mut.get("intermediates", {})
                    )
                    if any(
                        getattr(k, "key", None) == "moe_aux_loss" for k in path
                    )
                ]
                if aux:
                    loss = loss + moe_aux_weight * (
                        sum(aux) / len(aux)
                    ).astype(loss.dtype)
            return loss

        loss, grads = jax.value_and_grad(loss_fn)(state.params)

        if cross_host:
            flat, unravel = ravel_pytree(grads)
            if grad_compression == "bf16":
                reduced = dcn_pmean(flat.astype(jnp.bfloat16)).astype(flat.dtype)
            else:
                reduced = dcn_pmean(flat)
            grads = unravel(reduced)

        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return jax.jit(train_step, donate_argnums=(0,) if donate else ())


def synthetic_batch(rng: np.random.Generator, batch: int, image_size: int,
                    num_classes: int, channels: int = 3):
    """Random NHWC images + integer labels (the synthetic-benchmark diet)."""
    images = rng.standard_normal((batch, image_size, image_size, channels)).astype(np.float32)
    labels = rng.integers(0, num_classes, size=(batch,)).astype(np.int32)
    return images, labels
