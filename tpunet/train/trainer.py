"""Jitted training step with mesh shardings + optional cross-host gradient
sync over the tpunet DCN transport.

Design (TPU-first):
  * One jitted function contains forward, backward, and update — XLA fuses
    elementwise ops into the matmuls and inserts ICI collectives from the
    array shardings (batch over `dp`, Megatron-split classifier over `mdl`).
  * Cross-host gradient sync flattens the whole gradient pytree into ONE
    contiguous vector before the DCN all-reduce (`ravel_pytree`), so the
    multi-stream transport stripes a single large message instead of
    dribbling per-layer buffers — the same bucketing insight behind the
    reference's fairness design (large chunked messages saturate parallel
    streams; reference SURVEY §2.2 step 5).
"""

from __future__ import annotations

import re
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.flatten_util import ravel_pytree


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray


def create_train_state(model, rng, sample_input, tx) -> tuple[TrainState, Any]:
    """Initialize params + optimizer state. Returns (state, apply_fn)."""
    params = model.init(rng, sample_input)["params"]
    opt_state = tx.init(params)
    return TrainState(params, opt_state, jnp.zeros((), jnp.int32)), model.apply


def _backward_order_key(path_str: str):
    """Sort key approximating backward completion order: output-side layers
    (lm_head, final norm) first, transformer blocks in descending index,
    embeddings last. A scheduling HINT only — each bucket's start callback
    fires once its gradients exist, so matching the backward order maximizes
    compute/transfer overlap, but correctness never depends on it."""
    m = re.search(r"block(\d+)", path_str)
    if m:
        return (1, -int(m.group(1)), path_str)
    if "embed" in path_str:
        return (2, 0, path_str)
    return (0, 0, path_str)


def _bucketed_dcn_pmean(grads, bucket_bytes: int, compression: str | None, world: int):
    """Mean-all-reduce the gradient pytree over DCN in byte-bounded buckets,
    nonblocking: every bucket's reduction is SUBMITTED (dcn_all_reduce_start)
    before any is WAITED (dcn_all_reduce_finish), so the native worker thread
    reduces bucket k while XLA still computes the gradients feeding bucket
    k+1 — the overlap that produced the reference's end-to-end VGG16 win
    (reference README.md:52-84; request depth per cc/nccl_types.h:50)."""
    from tpunet.interop import dcn_all_reduce_finish, dcn_all_reduce_start

    leaves_with_path = jax.tree_util.tree_leaves_with_path(grads)
    treedef = jax.tree_util.tree_structure(grads)
    # float0 leaves (frozen integer params under allow_int — QLoRA's int8
    # base) carry no gradient to reduce and cannot be concatenated; they
    # pass straight through to the reconstruction below.
    reducible = [i for i, (_, leaf) in enumerate(leaves_with_path)
                 if leaf.dtype != jax.dtypes.float0]
    order = sorted(
        reducible,
        key=lambda i: _backward_order_key(jax.tree_util.keystr(leaves_with_path[i][0])),
    )

    # Greedy byte-bounded buckets in backward order; same-dtype within a
    # bucket (they concatenate into one flat vector).
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_bytes = 0
    for i in order:
        leaf = leaves_with_path[i][1]
        nb = leaf.size * leaf.dtype.itemsize
        if cur and (
            cur_bytes + nb > bucket_bytes
            or leaf.dtype != leaves_with_path[cur[0]][1].dtype
        ):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nb
    if cur:
        buckets.append(cur)

    # Phase 1: submit every bucket. Phase 2: collect. The ordered-callback
    # token chain keeps submission order identical on all ranks.
    flats, tickets = [], []
    for b in buckets:
        flat = jnp.concatenate([leaves_with_path[i][1].reshape(-1) for i in b])
        if compression == "bf16":
            flat = flat.astype(jnp.bfloat16)
        tickets.append(dcn_all_reduce_start(flat))
        flats.append(flat)

    new_leaves: list[Any] = [leaf if leaf.dtype == jax.dtypes.float0
                             else None
                             for _, leaf in leaves_with_path]
    for b, flat, ticket in zip(buckets, flats, tickets):
        reduced = dcn_all_reduce_finish(ticket, flat)
        off = 0
        for i in b:
            leaf = leaves_with_path[i][1]
            seg = reduced[off : off + leaf.size].astype(leaf.dtype)
            new_leaves[i] = seg.reshape(leaf.shape) / world
            off += leaf.size
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


def _wire_handles_bf16() -> bool:
    """True when the native communicator already compresses f32 payloads to
    bf16 ON THE WIRE (wire_dtype="bf16" / TPUNET_WIRE_DTYPE=bf16 — see
    docs/DESIGN.md "Compressed collectives"). The trainer then ships f32
    gradients straight through — ONE cast path, at the wire hop, with f32
    accumulation inside the ring — instead of double-casting in JAX and
    reducing in bf16. Communicators without the codec (f32-wire, or an
    emulated backend without a wire_dtype at all) keep the pure-Python
    bf16 cast."""
    from tpunet import distributed

    if not distributed.is_initialized():
        return False
    return getattr(distributed.global_communicator(), "wire_dtype", "f32") == "bf16"


def _make_loss_fn(model, images, labels, dropout_rng, moe_aux_weight: float,
                  fused_xent_block: int | None = None,
                  z_loss: float = 0.0):
    """The train-step objective, shared by the replicated and ZeRO paths:
    token/label cross-entropy plus (for MoE models) the Switch router's sown
    load-balancing losses, collected via mutable=['intermediates'] — without
    that term the router can collapse onto one expert.

    fused_xent_block: compute the cross-entropy blockwise over the vocab
    (tpunet.ops.blockwise_cross_entropy) so the (batch, seq, vocab) logits
    are never materialized — requires a model supporting
    ``features_only=True`` (the Transformer family) whose lm head lives at
    params['lm_head']['kernel']. KNOWN LIMIT: the fused path reads the head
    kernel directly, so under Megatron TP (lm_head split over tp_axis) GSPMD
    gathers the kernel and replicates the head compute — numerically fine,
    but the head's TP speedup is lost; prefer the default path when the lm
    head is tensor-parallel."""
    has_moe = getattr(model, "n_experts", 0) > 0
    if fused_xent_block is not None and getattr(model, "tp_axis", None):
        import warnings

        warnings.warn(
            "fused_xent_block with a tensor-parallel lm head replicates the "
            "head compute (kernel is gathered); the TP head speedup is lost",
            stacklevel=3,
        )

    fused = fused_xent_block is not None
    def loss_fn(p):
        out = model.apply(
            {"params": p}, images, train=True, rngs={"dropout": dropout_rng},
            mutable=["intermediates"] if has_moe else False,
            **({"features_only": True} if fused else {}),
        )
        out, mut = out if has_moe else (out, None)
        if fused:
            from tpunet.ops import blockwise_cross_entropy

            nll, lse = blockwise_cross_entropy(
                out.reshape(-1, out.shape[-1]),
                p["lm_head"]["kernel"],
                labels.reshape(-1),
                block_vocab=fused_xent_block,
                return_lse=True,
            )
            loss = nll.mean()
            if z_loss:
                loss = loss + z_loss * jnp.mean(jnp.square(lse))
        elif z_loss:
            # Single pass over the logits IN THEIR OWN DTYPE: lse feeds
            # BOTH the nll (lse - picked, optax's own identity, same dtype
            # semantics as the z=0 branch) and the z term — no second
            # logsumexp, no upcast copy of the logits tensor.
            lse = jax.scipy.special.logsumexp(out, axis=-1)
            picked = jnp.take_along_axis(
                out, labels[..., None], axis=-1)[..., 0]
            loss = (lse - picked).mean() + z_loss * jnp.mean(
                jnp.square(lse.astype(jnp.float32)))
        else:
            loss = optax.softmax_cross_entropy_with_integer_labels(out, labels)
            loss = loss.mean()
        if has_moe:
            # flax wraps sown values in tuples: sum leaves on matching paths
            # and average over MoE blocks.
            aux = [
                leaf
                for path, leaf in jax.tree_util.tree_leaves_with_path(
                    mut.get("intermediates", {})
                )
                if any(getattr(k, "key", None) == "moe_aux_loss" for k in path)
            ]
            if aux:
                loss = loss + moe_aux_weight * (sum(aux) / len(aux)).astype(loss.dtype)
        return loss

    return loss_fn


def _grad_zeros(p):
    """Zero gradient accumulator for one param leaf: ordinary zeros for
    inexact dtypes, a float0 placeholder for integer leaves (QLoRA's
    frozen int8 base) — float0 is what allow_int gradients produce, and
    it never accumulates or divides."""
    import numpy as np

    if jnp.issubdtype(p.dtype, jnp.inexact):
        return jnp.zeros_like(p)
    return np.zeros(p.shape, jax.dtypes.float0)


def _grad_add(acc, g):
    return acc if acc.dtype == jax.dtypes.float0 else jnp.add(acc, g)


def _apply_updates(params, updates):
    """optax.apply_updates with float0 pass-through: a float0 update
    (integer leaf under allow_int — QLoRA's frozen int8 base) leaves the
    leaf untouched; fp updates apply with the usual cast back to the
    param dtype."""
    return jax.tree.map(
        lambda p, u: p if u.dtype == jax.dtypes.float0
        else jnp.asarray(p + u, p.dtype), params, updates)


def _value_and_grads(model, params, images, labels, dropout_rng,
                     moe_aux_weight: float, fused_xent_block: int | None,
                     accum_steps: int | None, z_loss: float = 0.0):
    """(mean loss, mean grads) for the batch — in one backward, or (with
    accum_steps=k) as a lax.scan over k microbatches whose activations are
    freed between iterations: the throughput-neutral way to run a batch k×
    larger than activation memory allows. For dense models equal
    microbatches make the mean-of-means exactly the full-batch mean; MoE
    models route and compute expert capacity PER MICROBATCH (capacity =
    f(micro tokens), aux loss is batch-nonlinear), the standard practice but
    a slightly different objective than one full-batch step."""
    if accum_steps is None or accum_steps == 1:
        loss_fn = _make_loss_fn(model, images, labels, dropout_rng,
                                moe_aux_weight, fused_xent_block, z_loss)
        # allow_int: identical for ordinary fp trees, and lets a QLoRA
        # tree (frozen int8 base leaves inside params) differentiate —
        # the int leaves come back as float0, which _apply_updates and
        # the float0-aware accumulation below treat as "frozen".
        return jax.value_and_grad(loss_fn, allow_int=True)(params)

    batch = images.shape[0]
    if batch % accum_steps != 0:
        raise ValueError(f"batch {batch} not divisible by accum_steps {accum_steps}")
    micro = batch // accum_steps
    # STRIDED microbatches (row r -> microbatch r % k), not contiguous
    # blocks: under a dp-sharded batch axis, contiguous blocks would put a
    # whole microbatch on a subset of dp ranks (idling the rest each scan
    # step), while strided grouping keeps every rank's shard contributing
    # rows to every microbatch. Any equal-size grouping preserves the
    # mean-of-means identity, so numerics don't care.
    images_mb = images.reshape(micro, accum_steps, *images.shape[1:]).swapaxes(0, 1)
    labels_mb = labels.reshape(micro, accum_steps, *labels.shape[1:]).swapaxes(0, 1)
    keys = jax.random.split(dropout_rng, accum_steps)

    def body(carry, xs):
        loss_sum, grad_sum = carry
        im, lb, key = xs
        loss_fn = _make_loss_fn(model, im, lb, key, moe_aux_weight,
                                fused_xent_block, z_loss)
        loss, grads = jax.value_and_grad(loss_fn, allow_int=True)(params)
        return (loss_sum + loss,
                jax.tree.map(_grad_add, grad_sum, grads)), None

    init = (jnp.zeros((), jnp.float32), jax.tree.map(_grad_zeros, params))
    (loss_sum, grad_sum), _ = jax.lax.scan(body, init, (images_mb, labels_mb, keys))
    return loss_sum / accum_steps, jax.tree.map(
        lambda g: g if g.dtype == jax.dtypes.float0 else g / accum_steps,
        grad_sum
    )


def make_train_step(model, tx, cross_host: bool = False, donate: bool = True,
                    grad_compression: str | None = None,
                    moe_aux_weight: float = 0.01,
                    bucket_bytes: int | None = None,
                    fused_xent_block: int | None = None,
                    accum_steps: int | None = None,
                    z_loss: float = 0.0):
    """Build the jitted train step.

    cross_host=True adds the DCN gradient all-reduce tier (requires
    tpunet.distributed.initialize() BEFORE the first trace — the decision
    is baked into the executable).

    grad_compression="bf16" casts the flattened gradient vector to bfloat16
    before the cross-host all-reduce and back after — halving DCN bytes for
    ~1 ulp of bf16 noise on already-noisy SGD gradients (the reference has
    no compression; its parent project's QAdam/bytegrad live a layer above —
    this is that capability at the transport-facing tier).

    When the model has MoE blocks (``n_experts > 0``), the Switch router's
    sown load-balancing losses are collected via mutable=['intermediates']
    and added to the loss scaled by ``moe_aux_weight`` — without this term
    the router can collapse onto one expert and capacity-drop most tokens.

    bucket_bytes (cross_host only): sync gradients in byte-bounded buckets
    via NONBLOCKING all-reduces instead of one flat blocking vector, so DCN
    transfer overlaps backward compute (see _bucketed_dcn_pmean). None keeps
    the single-vector path.
    """
    if grad_compression not in (None, "bf16"):
        raise ValueError(f"unknown grad_compression {grad_compression!r}")
    if bucket_bytes is not None and not cross_host:
        raise ValueError("bucket_bytes requires cross_host=True")
    if cross_host:
        # Import here so single-host training never touches the transport.
        from tpunet import distributed
        from tpunet.interop import dcn_pmean

        world = distributed.world_size()  # raises early if initialize() was skipped
        # One cast path: when the wire already compresses to bf16, ship f32
        # gradients and let the ring quantize at the hops (f32 accumulation;
        # strictly better numerics than reducing in bf16). Decided at trace
        # time like every other cross-host choice.
        if grad_compression == "bf16" and _wire_handles_bf16():
            grad_compression = None

    def train_step(state: TrainState, images, labels, dropout_rng):
        loss, grads = _value_and_grads(model, state.params, images, labels,
                                       dropout_rng, moe_aux_weight,
                                       fused_xent_block, accum_steps, z_loss)

        if cross_host:
            if bucket_bytes is not None:
                grads = _bucketed_dcn_pmean(grads, bucket_bytes, grad_compression, world)
            else:
                # ravel_pytree cannot flatten float0 leaves (QLoRA's frozen
                # int8 base under allow_int): partition them out, reduce
                # the inexact vector, reinsert the placeholders.
                leaves, treedef = jax.tree_util.tree_flatten(grads)
                f0 = [leaf.dtype == jax.dtypes.float0 for leaf in leaves]
                flat, unravel = ravel_pytree(
                    [leaf for leaf, skip in zip(leaves, f0) if not skip])
                if grad_compression == "bf16":
                    reduced = dcn_pmean(flat.astype(jnp.bfloat16)).astype(flat.dtype)
                else:
                    reduced = dcn_pmean(flat)
                it = iter(unravel(reduced))
                grads = jax.tree_util.tree_unflatten(
                    treedef,
                    [leaf if skip else next(it)
                     for leaf, skip in zip(leaves, f0)])

        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = _apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return jax.jit(train_step, donate_argnums=(0,) if donate else ())


def _zero_shard_geometry(n: int, world: int) -> tuple[int, int]:
    """(padded_size, shard_size) for an n-element flat vector over `world`
    equal shards."""
    pad = (-n) % world
    return n + pad, (n + pad) // world


def create_zero_train_state(model, rng, sample_input, tx) -> tuple[TrainState, Any]:
    """ZeRO-1 companion to create_train_state: the optimizer state is built
    on THIS RANK's flat parameter shard (1/world of the elements), not the
    full pytree — the memory that dominates adamw training (2 f32 moments
    per parameter) shrinks by the DCN world size. Requires
    tpunet.distributed.initialize() first; every rank must call it."""
    from tpunet import distributed

    world = distributed.world_size()
    rank = distributed.rank()
    params = model.init(rng, sample_input)["params"]
    flat, _ = ravel_pytree(params)
    padded, shard_n = _zero_shard_geometry(flat.size, world)
    if padded != flat.size:
        flat = jnp.concatenate([flat, jnp.zeros(padded - flat.size, flat.dtype)])
    shard = jax.lax.dynamic_slice(flat, (rank * shard_n,), (shard_n,))
    return TrainState(params, tx.init(shard), jnp.zeros((), jnp.int32)), model.apply


def make_zero_train_step(model, tx, donate: bool = True,
                         grad_compression: str | None = None,
                         moe_aux_weight: float = 0.01,
                         fused_xent_block: int | None = None,
                         accum_steps: int | None = None,
                         z_loss: float = 0.0):
    """ZeRO-1 (optimizer-state sharding) cross-host train step.

    Instead of all-reducing the full gradient and updating replicated
    optimizer state (make_train_step cross_host=True), each step:
      1. reduce-scatters the flat gradient over DCN — each rank receives the
         MEAN of its 1/world shard (same wire bytes as ring all-reduce's RS
         phase; the reference's parent project ships sharded optimizers a
         layer above its transport — this is that capability here),
      2. applies `tx` to the shard against the matching parameter shard
         (update FLOPs and optimizer memory both /world),
      3. all-gathers the updated parameter shards (the AG phase's bytes).
    Total DCN traffic equals the all-reduce path; memory and update compute
    drop by world. The trajectory matches the replicated path to float
    rounding: the ring all-reduce computes each element's sum in exactly the
    RS phase this path runs, and adamw/sgd are elementwise, so sharding the
    vector does not reorder any per-element arithmetic.

    State must come from create_zero_train_state (sharded opt_state).
    grad_compression="bf16" halves the reduce-scatter bytes (the gather of
    updated params stays full precision).

    Elastic caveat: the opt-state shard geometry bakes in (rank, world) at
    trace time, so after an elastic rebuild that CHANGES the world size
    (allow_shrink) the sharded opt state is invalid — rebuild it with
    create_zero_train_state and restore params (not opt state) from the
    checkpoint. Fixed-world rebuilds (replacement policy) resume fine.
    """
    if grad_compression not in (None, "bf16"):
        raise ValueError(f"unknown grad_compression {grad_compression!r}")
    from tpunet import distributed
    from tpunet.interop import dcn_all_gather, dcn_reduce_scatter

    world = distributed.world_size()
    rank = distributed.rank()
    # One cast path (see make_train_step): the native wire codec quantizes
    # the reduce-scatter's hops itself, with f32 accumulation.
    if grad_compression == "bf16" and _wire_handles_bf16():
        grad_compression = None

    def train_step(state: TrainState, images, labels, dropout_rng):
        loss, grads = _value_and_grads(model, state.params, images, labels,
                                       dropout_rng, moe_aux_weight,
                                       fused_xent_block, accum_steps, z_loss)

        gflat, _ = ravel_pytree(grads)
        pflat, unravel = ravel_pytree(state.params)
        n = pflat.size
        padded, shard_n = _zero_shard_geometry(n, world)
        if padded != n:
            zpad = jnp.zeros(padded - n, gflat.dtype)
            gflat = jnp.concatenate([gflat, zpad])
            pflat = jnp.concatenate([pflat, zpad.astype(pflat.dtype)])

        if grad_compression == "bf16":
            gshard = dcn_reduce_scatter(gflat.astype(jnp.bfloat16))
            gshard = gshard.astype(gflat.dtype) / world
        else:
            gshard = dcn_reduce_scatter(gflat) / world
        pshard = jax.lax.dynamic_slice(pflat, (rank * shard_n,), (shard_n,))

        updates, opt_state = tx.update(gshard, state.opt_state, pshard)
        new_pshard = optax.apply_updates(pshard, updates)

        gathered = dcn_all_gather(new_pshard).reshape(-1)[:n]
        params = unravel(gathered)
        return TrainState(params, opt_state, state.step + 1), loss

    return jax.jit(train_step, donate_argnums=(0,) if donate else ())


def synthetic_batch(rng: np.random.Generator, batch: int, image_size: int,
                    num_classes: int, channels: int = 3):
    """Random NHWC images + integer labels (the synthetic-benchmark diet)."""
    images = rng.standard_normal((batch, image_size, image_size, channels)).astype(np.float32)
    labels = rng.integers(0, num_classes, size=(batch,)).astype(np.int32)
    return images, labels
