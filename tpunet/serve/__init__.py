"""Disaggregated prefill/decode serving tier over the tpunet transport.

The single-host inference stack (BatchServer continuous batching, per-row
KV cache) crosses the DCN here: **prefill ranks** run prompt ingestion and
produce KV blocks, **decode ranks** run the BatchServer slot machine, and
the blocks ship between them over the transport's multi-stream P2P path
using the block-scaled wire codec (int8 by default — the EQuARX
|err| <= amax/254 bound and its goldens carry over unchanged; f32 makes
the wire exact and the greedy output stream bitwise-equal to single-host
serving).

Layers (docs/DESIGN.md "Serving tier"):

  kv        KV-block flatten/encode/decode + model signature
  protocol  tier wiring handshake (typed mismatch on every rank) and the
            CRC-covered block/first/result frames
  prefill   PrefillEngine — the frontend's prompt-ingestion engine
  router    Router — admission, least-loaded placement, backpressure,
            failover (replay-from-KV / re-prefill), TTFT/TPOT SLO export
  decode    DecodeWorker — the decode rank's serve loop (adopts shipped
            KV into BatchServer slots, never re-prefills)
  publish   WeightPublisher/WeightReceiver — zero-downtime live weight
            updates: version-stamped checkpoint hot-swap over a
            bulk-class tree broadcast, flipped only behind a fleet-wide
            CRC32C gate and only at request boundaries

Minimal two-process setup::

    # decode box
    worker = serve.connect_decode("10.0.0.1:7100", model, params,
                                  slots=8, max_len=512)
    worker.serve()

    # frontend box
    pe = serve.PrefillEngine(model, params, max_len=512)
    router = serve.Router(pe)
    lsock = serve.Router.listen("0.0.0.0:7100")
    router.accept_ranks(lsock, n=1)
    rid = router.submit(prompt_tokens, max_new_tokens=64)
    tokens = router.run()[rid]

Env knobs (registered in Config.from_env): TPUNET_KV_WIRE_DTYPE,
TPUNET_ROUTER_POLICY, TPUNET_SERVE_ROLE, TPUNET_SWAP_TIMEOUT_MS,
TPUNET_SWAP_CHUNK_BYTES, TPUNET_PUBLISH_CLASS.
"""

from tpunet.serve.decode import DecodeWorker, connect as connect_decode  # noqa: F401
from tpunet.serve.kv import (  # noqa: F401
    KV_CODECS,
    decode_kv_block,
    encode_kv_block,
    kv_block_elems,
    kv_wire_bytes,
    model_signature,
)
from tpunet.serve.prefill import PrefillEngine  # noqa: F401
from tpunet.serve.protocol import (  # noqa: F401
    Hello,
    FrameLink,
    KVCodecMismatchError,
    KVIntegrityError,
    NoLiveDecodeRankError,
    RouterBusyError,
    ServeError,
    SwapAnnounce,
    TierMismatchError,
    TierProtocolError,
    wire_decode,
    wire_frontend,
)
from tpunet.serve.publish import (  # noqa: F401
    WeightPublisher,
    WeightReceiver,
    WeightSwapError,
    flatten_params,
    parse_swap_script,
    roundtrip_params,
    swap_action,
    swap_pending,
    unflatten_params,
)
from tpunet.serve.router import Router  # noqa: F401
