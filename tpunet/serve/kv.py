"""KV-block wire codec for the disaggregated serving tier.

A *KV block* is one request's prompt K/V — the ``cached_key`` /
``cached_value`` prefixes ``[0:plen]`` of every layer, in
``generate._kv_leaves`` order — flattened to ONE f32 vector and encoded
with the collective wire codec (``tpunet_c_codec_encode``): f32
passthrough, bf16 RNE, or block-scaled int8 with the EQuARX-derived
|err| <= amax/254 bound. Because each block is a single encode call, the
int8 scale blocks RESTART PER KV BLOCK (first 4 wire bytes = f32 scale of
the first 256 elements) and non-finite inputs poison their scale block to
NaN loudly — the exact properties the codec goldens pin, carried over
unchanged.

The final-position logits ride NEXT TO the block as raw f32, never through
the codec: the first sampled token stays exact under every KV codec, so an
int8 wire approximates only the attention context, not the sampling
distribution it was prefilled for.
"""

from __future__ import annotations

import math

import numpy as np

from tpunet import transport

#: Wire dtypes a KV block can ship as (the collective codec vocabulary).
KV_CODECS = ("f32", "bf16", "int8")


def kv_block_elems(shapes: list[tuple]) -> int:
    """Total f32 element count of a KV block with the given per-leaf shapes
    (``BatchServer.kv_leaf_shapes`` / ``PrefillEngine.kv_leaf_shapes``)."""
    return sum(int(math.prod(s)) for s in shapes)


def kv_wire_bytes(codec: str, shapes: list[tuple]) -> int:
    """Encoded byte count of a KV block under ``codec`` — the exact sizing
    rule both tiers frame against (bf16: 2n; int8: n + 4*ceil(n/256))."""
    return transport.codec_wire_bytes(codec, kv_block_elems(shapes))


def encode_kv_block(kv_rows: list[np.ndarray], codec: str) -> np.ndarray:
    """Flatten the per-leaf KV prefixes into one f32 vector and encode it
    with the wire codec (ONE encode call — int8 scale blocks restart here).
    Returns the wire bytes (uint8). Feeds ``tpunet_codec_bytes_total`` /
    ``tpunet_codec_wire_ratio`` like every other codec call."""
    if codec not in KV_CODECS:
        raise ValueError(f"unknown KV wire codec {codec!r}")
    flat = np.concatenate(
        [np.ascontiguousarray(b, np.float32).ravel() for b in kv_rows])
    return transport.codec_encode(flat, codec)


def decode_kv_block(wire, codec: str, shapes: list[tuple]) -> list[np.ndarray]:
    """Decode a KV block's wire bytes back into per-leaf f32 arrays of
    ``shapes`` (the receiver's ``kv_leaf_shapes(plen)``) — the adopt-side
    half of the round trip. Raises ValueError when the wire size does not
    match the shapes' encoded size."""
    if codec not in KV_CODECS:
        raise ValueError(f"unknown KV wire codec {codec!r}")
    n = kv_block_elems(shapes)
    flat = transport.codec_decode(np.frombuffer(bytes(wire), np.uint8), codec, n)
    out = []
    off = 0
    for s in shapes:
        m = int(math.prod(s))
        out.append(flat[off:off + m].reshape(s))
        off += m
    return out


def model_signature(model) -> int:
    """Config fingerprint both tiers must agree on before any KV block can
    be interpreted: CRC32C of the module's repr (flax dataclass — captures
    vocab, depth, heads, dims, window, cache flavor). Parameter VALUES are
    deliberately not covered (too big to hash at wiring); mismatched
    weights produce wrong tokens, not mis-framed wire bytes."""
    return transport.crc32c(repr(model).encode())
