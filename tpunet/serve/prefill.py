"""Prefill tier: prompt ingestion -> shippable KV blocks.

The prefill rank runs EXACTLY the computation the single-host BatchServer's
refill path runs — the same ``_prefill`` on the same (1, p) shapes with the
same decode/per-row clone — so the extracted K/V prefix and final-position
logits are bitwise what a local prefill would have produced. That identity
is the whole disaggregation contract: ship those bytes over an exact (f32)
wire, adopt them into a decode slot, and the greedy token stream cannot be
told apart from single-host serving.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from tpunet.models.generate import (_kv_leaves, _prefill, _set_cache_index,
                                    init_cache)


class PrefillEngine:
    """One-slot prompt-ingestion engine for the frontend tier.

    Holds a single persistent decode-cache row (donated through every
    call, like the BatchServer's); each ``prefill()`` resets the row's
    index and fills positions ``[0, p)``, then extracts the per-layer
    K/V prefixes in ``_kv_leaves`` order plus the last-position logits.
    One retrace per distinct prompt length — bucket or pad prompt lengths
    exactly as with any static-shape serving stack.
    """

    def __init__(self, model, params, *, max_len: int,
                 prefill_chunk: int | None = None):
        if getattr(model, "n_experts", 0):
            raise ValueError(
                "PrefillEngine requires a dense model (same MoE "
                "batch-coupling argument as the BatchServer)")
        if getattr(model, "attn_window", None) is not None:
            raise ValueError(
                "PrefillEngine requires a full-capacity cache: windowed "
                "ring caches do not keep the shipped-prefix layout")
        self.model = model
        self.max_len = max_len
        self._dm = model.clone(decode=True, per_row_cache=True)
        self._cache = init_cache(self._dm, 1, max_len)
        self._chunk = prefill_chunk
        self.stats = {"prefills": 0}
        params_c = params

        @partial(jax.jit, donate_argnums=(0,), static_argnames=("chunk",))
        def prefill_one(cache, prompt, chunk):
            cache = _set_cache_index(cache, 0)
            return _prefill(self._dm, params_c, cache, prompt, chunk)

        self._prefill_one = prefill_one

    def kv_leaf_shapes(self, plen: int) -> list[tuple]:
        """Per-leaf KV block shapes for a prompt of length `plen` — must
        equal the decode tier's ``BatchServer.kv_leaf_shapes(plen)``."""
        return [(plen,) + tuple(leaf.shape[2:])
                for leaf in _kv_leaves(self._cache)]

    def prefill(self, prompt) -> tuple[list[np.ndarray], np.ndarray]:
        """Run prompt ingestion; returns (kv_rows, last_logits) — the
        per-leaf f32 K/V prefixes and the final-position logit row, ready
        for ``serve.kv.encode_kv_block`` / ``BatchServer.submit_kv``."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size < 1:
            raise ValueError(
                f"prompt must be 1-D non-empty, got shape {prompt.shape}")
        if prompt.size >= self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) must leave room for generation "
                f"under max_len {self.max_len}")
        self._cache, last = self._prefill_one(
            self._cache, jnp.asarray(prompt[None]), self._chunk)
        plen = prompt.size
        kv_rows = [np.asarray(leaf[0, :plen], np.float32)
                   for leaf in _kv_leaves(self._cache)]
        self.stats["prefills"] += 1
        return kv_rows, np.asarray(last[0], np.float32)
