"""Tier wiring + KV shipping protocol for the disaggregated serving tier.

Two layers, mirroring the collective bootstrap's shape (PR 5/6):

**Tier wiring (out-of-band TCP).** The frontend (router + prefill) listens
on a plain TCP port; each decode rank connects. Both sides exchange a
fixed-size HELLO — protocol version, role, KV wire codec, slots, max_len,
vocab, model-config signature — and EACH side validates the peer's before
touching any payload: a disagreement raises a typed error on EVERY rank
(``KVCodecMismatchError`` for the codec, ``TierMismatchError`` for the
rest), exactly like the collective codec/algo handshake. Only then do the
sides swap transport listen handles and bring up a full-duplex pair of
tpunet P2P comms (frontend->decode for KV blocks, decode->frontend for
first-token/result frames), so the bulk path rides the multi-stream
engine — CRC trailers, fault injection, failover, telemetry and all.

**Frames (over the transport).** Every frame is two messages: a fixed
24-byte header (magic, version, type, request id, body length, aux) and a
body of ``body_len`` payload bytes plus a CRC32C trailer covering
header + payload. A corrupt frame raises ``KVIntegrityError``; an alien or
wrong-version header raises ``TierProtocolError``. Block frames carry the
codec id redundantly and the receiver cross-checks it against the wiring
negotiation — belt over suspenders, typed either way.
"""

from __future__ import annotations

import socket
import struct
import time

import numpy as np

from tpunet import transport
from tpunet._native import QosAdmissionError

MAGIC = b"TPKV"
VERSION = 1

# Frame types.
T_BLOCK = 1      # frontend -> decode: one request's prompt + logits + KV
T_FIRST = 2      # decode -> frontend: request's first token committed
T_RESULT = 3     # decode -> frontend: request finished (tokens + timing)
T_SHUTDOWN = 4   # frontend -> decode: drain live requests, then exit
# Live weight updates (docs/DESIGN.md "Live weight updates"): the swap
# control plane rides the SAME latency-class tier links as requests — only
# the weight bytes themselves go over the bulk-class broadcast comm.
T_SWAP_BEGIN = 5   # frontend -> decode: announce a publication (SwapAnnounce)
T_SWAP_STATUS = 6  # decode -> frontend: aux=1 flipped / aux=2 aborted, id=version
T_SWAP_RETIRE = 7  # frontend -> decode: drop version `aux` once locally drained

# Hello roles.
ROLE_FRONTEND = 0
ROLE_DECODE = 1

_HEADER = struct.Struct("<4sHHQII")     # magic, version, type, req_id, body_len, aux
_HELLO = struct.Struct("<4sHBBIIIIQ")   # magic, version, role, codec, slots,
                                        # max_len, vocab, traffic class (low
                                        # byte; rest reserved), model_sig
_BLOCK_HDR = struct.Struct("<IIIIB3x")  # plen, max_new, n_kv, vocab, codec
_RESULT_HDR = struct.Struct("<IIQ")     # ntok, status, tpot_us

_CODEC_IDS = {"f32": 0, "bf16": 1, "int8": 2}
_CODEC_NAMES = {v: k for k, v in _CODEC_IDS.items()}

# QoS traffic classes (tpunet.transport.TRAFFIC_CLASSES order — the native
# TrafficClass ints). KV BLOCK and FIRST/RESULT frames ship on a
# latency-class link by default so TTFT-bound traffic never queues behind a
# co-tenant's bulk gradient AllReduce (docs/DESIGN.md "Transport QoS").
_CLASS_IDS = {"latency": 0, "bulk": 1, "control": 2}
_CLASS_NAMES = {v: k for k, v in _CLASS_IDS.items()}


class ServeError(RuntimeError):
    """Base class for disaggregated-serving tier errors."""


class TierMismatchError(ServeError):
    """The two sides of a tier link disagree on the wiring contract
    (protocol version, role pairing, model signature, slots/max_len
    sanity). Raised on EVERY rank at wiring time — before any KV byte
    could be misinterpreted."""


class KVCodecMismatchError(TierMismatchError):
    """The tiers disagree on the KV wire codec (TPUNET_KV_WIRE_DTYPE /
    kv_codec=). Raised on every rank at tier wiring, naming both codecs —
    the serving-tier twin of the collective CodecMismatchError."""


class KVIntegrityError(ServeError):
    """A KV/result frame failed its CRC32C check. The link survives; the
    router treats the request like a decode-rank failure (replay or
    re-prefill) rather than ever emitting bytes from a corrupt frame."""


class TierProtocolError(ServeError):
    """A frame that is not tpunet serve protocol (bad magic / version /
    inconsistent sizes) arrived on a tier link."""


class RouterBusyError(ServeError):
    """Admission rejected: every decode slot is occupied and the router
    queue is at its backpressure limit. Retry later — nothing was
    enqueued."""


class NoLiveDecodeRankError(ServeError):
    """Every decode rank has failed; in-flight requests cannot be placed."""


def _crc_frame(header: bytes, payload) -> int:
    crc = transport.crc32c(header)
    if len(payload):
        crc = transport.crc32c(payload, seed=crc)
    return crc


class Hello:
    """One side's wiring contract (see module docstring)."""

    def __init__(self, role: int, kv_codec: str, slots: int, max_len: int,
                 vocab: int, model_sig: int, traffic_class: str = "latency",
                 weight_version: int = 0):
        if kv_codec not in _CODEC_IDS:
            raise ValueError(f"unknown KV wire codec {kv_codec!r}")
        if traffic_class not in _CLASS_IDS:
            raise ValueError(f"unknown traffic class {traffic_class!r}")
        if not 0 <= weight_version < (1 << 24):
            raise ValueError(
                f"weight_version must fit 24 bits, got {weight_version}")
        self.role = role
        self.kv_codec = kv_codec
        self.slots = slots
        self.max_len = max_len
        self.vocab = vocab
        self.model_sig = model_sig
        self.traffic_class = traffic_class
        # Checkpoint version this side serves. Rides the reserved upper
        # bytes of the traffic-class word, so old and new builds interop:
        # a pre-swap peer reads class-only (it masked the low byte all
        # along) and reports version 0 — which the router treats as "needs
        # catch-up", never a mismatch (mixed-version pools are LEGAL;
        # version skew is resolved by re-publication, not rejection).
        self.weight_version = weight_version

    def pack(self) -> bytes:
        return _HELLO.pack(MAGIC, VERSION, self.role,
                           _CODEC_IDS[self.kv_codec], self.slots,
                           self.max_len, self.vocab,
                           _CLASS_IDS[self.traffic_class]
                           | (self.weight_version << 8),
                           self.model_sig & 0xFFFFFFFFFFFFFFFF)

    @staticmethod
    def unpack(raw: bytes) -> "Hello":
        if len(raw) != _HELLO.size:
            raise TierProtocolError(
                f"tier hello is {len(raw)}B, want {_HELLO.size}B")
        magic, ver, role, codec, slots, max_len, vocab, cls, sig = \
            _HELLO.unpack(raw)
        if magic != MAGIC:
            raise TierProtocolError(
                f"tier hello has magic {magic!r}, want {MAGIC!r} — peer is "
                f"not a tpunet serving tier")
        if ver != VERSION:
            raise TierMismatchError(
                f"tier hello version {ver} != local {VERSION}")
        if codec not in _CODEC_NAMES:
            raise TierProtocolError(f"tier hello carries unknown codec id {codec}")
        if (cls & 0xFF) not in _CLASS_NAMES:
            raise TierProtocolError(
                f"tier hello carries unknown traffic class id {cls & 0xFF}")
        return Hello(role, _CODEC_NAMES[codec], slots, max_len, vocab, sig,
                     _CLASS_NAMES[cls & 0xFF], weight_version=cls >> 8)


def _check_peer(mine: Hello, peer: Hello, want_role: int) -> None:
    """Validate the peer's hello against ours — the typed-mismatch half of
    the wiring handshake. BOTH sides send before either reads, so a
    disagreement raises on every rank."""
    if peer.role != want_role:
        raise TierMismatchError(
            f"peer tier role is {peer.role}, want {want_role} (two "
            f"frontends or two decode ranks wired together)")
    if peer.kv_codec != mine.kv_codec:
        raise KVCodecMismatchError(
            f"KV wire codec mismatch: local {mine.kv_codec!r} vs peer "
            f"{peer.kv_codec!r} — set TPUNET_KV_WIRE_DTYPE (or kv_codec=) "
            f"identically on both tiers")
    if peer.traffic_class != mine.traffic_class:
        raise TierMismatchError(
            f"QoS traffic-class mismatch: local {mine.traffic_class!r} vs "
            f"peer {peer.traffic_class!r} — both tiers must wire the link "
            f"on the same lane (traffic_class= / TPUNET_TRAFFIC_CLASS)")
    if peer.model_sig != mine.model_sig:
        raise TierMismatchError(
            f"model-config signature mismatch: local {mine.model_sig:#x} "
            f"vs peer {peer.model_sig:#x} — the tiers are serving "
            f"different model configurations")
    if peer.vocab != mine.vocab:
        raise TierMismatchError(
            f"vocab mismatch: local {mine.vocab} vs peer {peer.vocab}")
    if peer.max_len != mine.max_len:
        raise TierMismatchError(
            f"max_len mismatch: local {mine.max_len} vs peer {peer.max_len}")


def _role_guard(my_role: int) -> None:
    """TPUNET_SERVE_ROLE cross-check: a box pinned to one tier role must
    not come up as the other (catches copy-pasted launch commands)."""
    from tpunet.config import Config

    configured = Config.from_env().serve_role
    want = {ROLE_FRONTEND: "frontend", ROLE_DECODE: "decode"}[my_role]
    if configured and configured != want:
        raise TierMismatchError(
            f"TPUNET_SERVE_ROLE={configured} but this process is wiring as "
            f"the {want} tier")


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        got = sock.recv(n - len(buf))
        if not got:
            raise TierProtocolError("tier peer closed during wiring handshake")
        buf += got
    return buf


class FrameLink:
    """Full-duplex framed channel over a pair of tpunet P2P comms."""

    def __init__(self, send_comm, recv_comm, peer: Hello, name: str = ""):
        self.send_comm = send_comm
        self.recv_comm = recv_comm
        self.peer = peer
        self.name = name
        self._hdr_buf = None
        self._hdr_req = None
        self._body_buf = None
        self._body_req = None
        self._hdr = None

    # -- sending -----------------------------------------------------------

    def send_frame(self, ftype: int, req_id: int, payload: bytes = b"",
                   aux: int = 0, timeout: float | None = 60.0) -> None:
        header = _HEADER.pack(MAGIC, VERSION, ftype, req_id, len(payload), aux)
        trailer = struct.pack("<I", _crc_frame(header, payload))
        # QoS admission backpressure (QosAdmissionError, -8): the HEADER
        # send is the atomic admission point — it fails with NOTHING on the
        # wire, so the caller (router) can safely requeue the whole frame.
        # Once the header is out, the body MUST follow or the link would
        # desync, so a body-side rejection retries in place: the class has
        # bytes in flight (at least our header), and an idle class always
        # admits, so this converges as the link drains.
        self.send_comm.send(header, timeout=timeout)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                self.send_comm.send(payload + trailer, timeout=timeout)
                return
            except QosAdmissionError:
                if deadline is not None and time.monotonic() > deadline:
                    raise
                time.sleep(0.001)

    # -- receiving ---------------------------------------------------------

    def poll(self):
        """Non-blocking receive: returns (ftype, req_id, payload, aux) when
        a whole frame has arrived, else None. Raises typed errors on
        protocol violations / CRC failure; transport errors (peer death,
        watchdog) surface as NativeError from the underlying comm."""
        if self._hdr_req is None:
            self._hdr_buf = bytearray(_HEADER.size)
            self._hdr_req = self.recv_comm.irecv(self._hdr_buf)
        if self._hdr is None:
            done, nbytes = self._hdr_req.test()
            if not done:
                return None
            if nbytes != _HEADER.size:
                raise TierProtocolError(
                    f"tier frame header is {nbytes}B, want {_HEADER.size}B")
            magic, ver, ftype, req_id, body_len, aux = _HEADER.unpack(
                bytes(self._hdr_buf))
            if magic != MAGIC:
                raise TierProtocolError(
                    f"tier frame magic {magic!r}, want {MAGIC!r}")
            if ver != VERSION:
                raise TierProtocolError(
                    f"tier frame version {ver} != local {VERSION}")
            self._hdr = (ftype, req_id, body_len, aux)
            self._body_buf = bytearray(body_len + 4)
            self._body_req = self.recv_comm.irecv(self._body_buf)
        done, nbytes = self._body_req.test()
        if not done:
            return None
        ftype, req_id, body_len, aux = self._hdr
        if nbytes != body_len + 4:
            raise TierProtocolError(
                f"tier frame body is {nbytes}B, header promised "
                f"{body_len + 4}B")
        body = bytes(self._body_buf)
        payload, (got_crc,) = body[:-4], struct.unpack("<I", body[-4:])
        want_crc = _crc_frame(bytes(self._hdr_buf), payload)
        # Consume the frame state BEFORE the CRC verdict so a corrupt frame
        # doesn't wedge the link for its successors.
        self._hdr = self._hdr_req = self._hdr_buf = None
        self._body_req = self._body_buf = None
        if got_crc != want_crc:
            raise KVIntegrityError(
                f"tier frame CRC mismatch (type {ftype}, request {req_id}): "
                f"got {got_crc:#010x}, want {want_crc:#010x}")
        return ftype, req_id, payload, aux

    def recv_frame(self, timeout: float = 60.0):
        """Blocking poll() with a deadline; raises TimeoutError."""
        deadline = time.monotonic() + timeout
        while True:
            frame = self.poll()
            if frame is not None:
                return frame
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no tier frame within {timeout}s on {self.name or 'link'}")
            time.sleep(0.0005)

    def close(self) -> None:
        for comm in (self.send_comm, self.recv_comm):
            try:
                comm.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


# -- block/result payload packing -------------------------------------------


def pack_block(prompt: np.ndarray, max_new: int, kv_wire: np.ndarray,
               n_kv: int, logits: np.ndarray, codec: str) -> bytes:
    """BLOCK payload: sub-header | prompt int32 | logits f32 (raw — the
    first token stays exact under every KV codec) | encoded KV bytes."""
    head = _BLOCK_HDR.pack(len(prompt), max_new, n_kv, len(logits),
                           _CODEC_IDS[codec])
    return (head + np.ascontiguousarray(prompt, np.int32).tobytes()
            + np.ascontiguousarray(logits, np.float32).tobytes()
            + bytes(kv_wire))


def unpack_block(payload: bytes, codec: str):
    """Parse a BLOCK payload -> (prompt, max_new, n_kv, logits, kv_wire).
    Cross-checks the frame's codec id against the wiring-negotiated one."""
    if len(payload) < _BLOCK_HDR.size:
        raise TierProtocolError("BLOCK payload shorter than its sub-header")
    plen, max_new, n_kv, vocab, codec_id = _BLOCK_HDR.unpack(
        payload[:_BLOCK_HDR.size])
    if _CODEC_NAMES.get(codec_id) != codec:
        raise TierProtocolError(
            f"BLOCK frame codec {_CODEC_NAMES.get(codec_id, codec_id)!r} != "
            f"wiring-negotiated {codec!r}")
    off = _BLOCK_HDR.size
    # Counts come off the wire: bound them against the actual payload BEFORE
    # np.frombuffer, whose "buffer is smaller than requested size" ValueError
    # is not a typed protocol error (found by tests/test_fuzz.py).
    if len(payload) - off < 4 * (plen + vocab):
        raise TierProtocolError(
            f"BLOCK sub-header claims {plen} prompt + {vocab} logit words "
            f"but only {len(payload) - off}B of payload follow")
    prompt = np.frombuffer(payload, np.int32, plen, off)
    off += 4 * plen
    logits = np.frombuffer(payload, np.float32, vocab, off)
    off += 4 * vocab
    wire = np.frombuffer(payload, np.uint8, offset=off)
    want = transport.codec_wire_bytes(codec, n_kv)
    if wire.size != want:
        raise TierProtocolError(
            f"BLOCK KV wire is {wire.size}B, {codec} x {n_kv} elements "
            f"encodes to {want}B")
    return prompt, max_new, n_kv, logits, wire


def pack_result(tokens: np.ndarray, status: int, tpot_us: int) -> bytes:
    return (_RESULT_HDR.pack(len(tokens), status, tpot_us)
            + np.ascontiguousarray(tokens, np.int32).tobytes())


def unpack_result(payload: bytes):
    if len(payload) < _RESULT_HDR.size:
        raise TierProtocolError("RESULT payload shorter than its sub-header")
    ntok, status, tpot_us = _RESULT_HDR.unpack(payload[:_RESULT_HDR.size])
    if len(payload) - _RESULT_HDR.size < 4 * ntok:
        raise TierProtocolError(
            f"RESULT sub-header claims {ntok} tokens but only "
            f"{len(payload) - _RESULT_HDR.size}B of payload follow")
    tokens = np.frombuffer(payload, np.int32, ntok, _RESULT_HDR.size)
    return tokens, status, tpot_us


# -- weight-swap announce payload --------------------------------------------

# SWAP_BEGIN sub-header: version, broadcast world size, the receiver's rank
# in it, total f32 elements across the flat parameter leaves, broadcast
# chunk size (bytes of encoded wire per tree broadcast), wire codec id,
# the QoS class the broadcast comm must wire on (the PUBLISHER is
# authoritative — receivers must not read their own env, or a half-fleet
# TPUNET_PUBLISH_CLASS drift would fail the comm negotiation), and the
# whole-swap deadline (ms). The rendezvous coordinator ("host:port")
# follows as UTF-8 — variable length, hence last.
_SWAP_HDR = struct.Struct("<IIIQIBBI")

# STATUS verdicts (the aux word of a T_SWAP_STATUS frame).
SWAP_FLIPPED = 1
SWAP_ABORTED = 2


class SwapAnnounce:
    """Parsed T_SWAP_BEGIN payload (see pack_swap_begin)."""

    def __init__(self, version: int, world: int, rank: int, nelems: int,
                 chunk_bytes: int, codec: str, timeout_ms: int,
                 coordinator: str, traffic_class: str = "bulk"):
        self.version = version
        self.world = world
        self.rank = rank
        self.nelems = nelems
        self.chunk_bytes = chunk_bytes
        self.codec = codec
        self.timeout_ms = timeout_ms
        self.coordinator = coordinator
        self.traffic_class = traffic_class


def pack_swap_begin(ann: SwapAnnounce) -> bytes:
    if ann.codec not in _CODEC_IDS:
        raise ValueError(f"unknown weight wire codec {ann.codec!r}")
    if ann.traffic_class not in _CLASS_IDS:
        raise ValueError(f"unknown traffic class {ann.traffic_class!r}")
    return (_SWAP_HDR.pack(ann.version, ann.world, ann.rank, ann.nelems,
                           ann.chunk_bytes, _CODEC_IDS[ann.codec],
                           _CLASS_IDS[ann.traffic_class], ann.timeout_ms)
            + ann.coordinator.encode())


def unpack_swap_begin(payload: bytes) -> SwapAnnounce:
    if len(payload) < _SWAP_HDR.size:
        raise TierProtocolError("SWAP_BEGIN payload shorter than its sub-header")
    version, world, rank, nelems, chunk_bytes, codec_id, cls_id, timeout_ms \
        = _SWAP_HDR.unpack(payload[:_SWAP_HDR.size])
    if codec_id not in _CODEC_NAMES:
        raise TierProtocolError(
            f"SWAP_BEGIN carries unknown codec id {codec_id}")
    if cls_id not in _CLASS_NAMES:
        raise TierProtocolError(
            f"SWAP_BEGIN carries unknown traffic class id {cls_id}")
    if not (0 < rank < world):
        raise TierProtocolError(
            f"SWAP_BEGIN rank {rank} outside broadcast world {world} "
            f"(rank 0 is the publisher — never a receiver)")
    coordinator = payload[_SWAP_HDR.size:].decode("utf-8", "replace")
    if ":" not in coordinator:
        raise TierProtocolError(
            f"SWAP_BEGIN coordinator {coordinator!r} is not host:port")
    return SwapAnnounce(version, world, rank, nelems, chunk_bytes,
                        _CODEC_NAMES[codec_id], timeout_ms, coordinator,
                        _CLASS_NAMES[cls_id])


# -- tier wiring -------------------------------------------------------------


def _swap_handles_and_connect(sock: socket.socket, net, accept_first: bool):
    """Exchange transport listen handles over the wiring socket and bring
    up the full-duplex comm pair. `accept_first` breaks the connect/accept
    symmetry (decode accepts before connecting; frontend the reverse)."""
    lc = net.listen()
    sock.sendall(lc.handle)
    peer_handle = _recv_exact(sock, len(lc.handle))
    if accept_first:
        rc = lc.accept()
        sc = net.connect(peer_handle)
    else:
        sc = net.connect(peer_handle)
        rc = lc.accept()
    lc.close()
    return sc, rc


def wire_frontend(conn: socket.socket, net, hello: Hello,
                  name: str = "") -> FrameLink:
    """Frontend half of the tier handshake over an ACCEPTED wiring socket:
    hello exchange (typed mismatch on every rank), handle swap, comm pair.
    Returns the decode rank's FrameLink."""
    _role_guard(ROLE_FRONTEND)
    conn.sendall(hello.pack())            # send BEFORE reading: both sides
    peer = Hello.unpack(_recv_exact(conn, _HELLO.size))  # get to validate
    _check_peer(hello, peer, ROLE_DECODE)
    sc, rc = _swap_handles_and_connect(conn, net, accept_first=False)
    return FrameLink(sc, rc, peer, name=name or "decode-link")


def wire_decode(addr: tuple[str, int] | str, net, hello: Hello,
                timeout: float = 60.0) -> FrameLink:
    """Decode-rank half: connect to the frontend's wiring port (retrying
    within `timeout` — the frontend may still be coming up), run the hello
    handshake, swap handles. Returns the frontend's FrameLink."""
    _role_guard(ROLE_DECODE)
    if isinstance(addr, str):
        host, _, port = addr.rpartition(":")
        addr = (host or "127.0.0.1", int(port))
    deadline = time.monotonic() + timeout
    while True:
        try:
            sock = socket.create_connection(addr, timeout=timeout)
            break
        except OSError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.05)
    try:
        sock.sendall(hello.pack())
        peer = Hello.unpack(_recv_exact(sock, _HELLO.size))
        _check_peer(hello, peer, ROLE_FRONTEND)
        sc, rc = _swap_handles_and_connect(sock, net, accept_first=True)
    finally:
        sock.close()
    return FrameLink(sc, rc, peer, name="frontend-link")
