"""Live weight updates: version-stamped hot-swap over the transport itself.

A running fleet must adopt a new checkpoint without dropping a request.
The publication path reuses the stack's own primitives end to end
(docs/DESIGN.md "Live weight updates"):

**Control plane on the latency links.** The frontend announces a swap with
a T_SWAP_BEGIN frame per decode rank (version, broadcast shape, chunk
size, wire codec, QoS class, rendezvous coordinator, deadline) on the
SAME latency-class tier links that carry requests — a few hundred bytes,
invisible to the schedulers. Receivers answer with T_SWAP_STATUS
(flipped/aborted) and the frontend retires drained versions with
T_SWAP_RETIRE.

**Weight bytes on the bulk class.** The checkpoint itself is flattened to
one f32 vector, encoded once under the bf16 wire codec, and chunk-streamed
through a binomial-tree ``Communicator.broadcast`` wired on the BULK QoS
class (``TPUNET_PUBLISH_CLASS``) — so the existing DRR scheduler keeps the
latency-class decode/KV traffic's p99 while gigabytes of weights flow.
The publisher interleaves its pump callback (``Router.poll``) between
chunks; receivers pump ONE chunk per serve-loop pass — neither side ever
parks its serving loop on the fat transfer.

**Flip only on proof, only at a request boundary.** After the last chunk,
every participant CRC32C-hashes the wire bytes it holds and all-gathers
the digests: the verdict is computed locally but identically on every
rank, so ONE corrupt receiver refuses the flip FLEET-WIDE with zero extra
frames. Only a verified rank stages the decoded parameters and flips —
between serve-loop iterations, never under a half-stepped batch. Every
failure path (death mid-broadcast, digest disagreement, deadline) raises
the typed retryable ``WeightSwapError`` (-10); the previous version keeps
serving throughout.

**Mixed-version pools are legal.** Each request is pinned at admission to
the version that prefilled it (the version rides the T_BLOCK aux word and
the HELLO signature's upper bytes); old versions serve their pinned
sessions until drained, then retire. A rank that rejoins stale (death
mid-swap) is caught up by a world=2 re-publication of the retained wire.

Scripted chaos composes: ``swap:at_step=N:action=publish|corrupt|die``
segments ride TPUNET_FAULT_SPEC next to ``churn`` ones; this module holds
the Python poll/parse mirror of the native slot (fault.cc).
"""

from __future__ import annotations

import contextlib
import os
import socket
import sys
import threading
import time

_DEBUG = bool(os.environ.get("TPUNET_SWAP_DEBUG"))


def _dbg(msg: str) -> None:
    if _DEBUG:
        print(f"[swapdbg {time.monotonic():.3f}] {msg}",
              file=sys.stderr, flush=True)

import numpy as np

from tpunet import _native, telemetry, transport
from tpunet._native import WeightSwapError
from tpunet.collectives import Communicator
from tpunet.serve import protocol as proto
from tpunet.serve.prefill import PrefillEngine

__all__ = [
    "WeightPublisher", "WeightReceiver", "WeightSwapError", "flatten_params",
    "parse_swap_script", "roundtrip_params", "swap_action", "swap_pending",
    "unflatten_params",
]

_SWAP_ACTIONS = {0: None, 1: "publish", 2: "corrupt", 3: "die"}

_ERR = _native.TPUNET_ERR_WEIGHT_SWAP

# How long past the swap deadline the publisher keeps pumping after
# force-closing the comm under a parked broadcast thread before it
# ABANDONS the (daemon) thread and raises typed. A peer SIGKILLed at the
# wrong instant can wedge the native collective in a state even close()
# cannot error out of; that must cost one leaked thread, never the
# serving loop.
_CAST_ABANDON_GRACE_S = 5.0


# -- scripted swap chaos (Python mirror of cpp/src/fault.cc) -----------------


def swap_action(step: int) -> str | None:
    """One-shot poll of the armed swap script (TPUNET_FAULT_SPEC /
    tpunet_c_fault_inject): the first un-fired ``swap:`` event with
    at_step <= step fires; returns "publish" (frontend: publish the staged
    checkpoint NOW), "corrupt" (decode: flip a byte of the received wire
    before digesting — the CRC-refusal drill), "die" (decode: SIGKILL
    yourself mid-swap) or None. Fired latches persist until DisarmFault."""
    lib = _native.load()
    code = int(lib.tpunet_c_swap_poll(int(step)))
    if code < 0:
        raise _native.NativeError(code, "swap_poll")
    return _SWAP_ACTIONS.get(code)


def swap_pending() -> int:
    """Armed swap events not yet fired (a finished scripted run must
    report 0 — the smoke lane's completeness gate)."""
    lib = _native.load()
    return int(lib.tpunet_c_swap_pending())


def parse_swap_script(spec: str) -> list[dict]:
    """Python mirror of the native swap-segment parser for harness-side
    scheduling (the native slot is poll-consuming; a harness that must know
    the publish schedule up front parses the same spec non-destructively).
    Returns [{"at_step", "action"}, ...] for the swap segments; churn and
    classic fault segments are ignored. Raises ValueError on a malformed
    swap segment, naming the offending token (the native parser rejects
    the same specs through tpunet_c_fault_inject)."""
    events: list[dict] = []
    for seg in (spec or "").split(";"):
        if not seg:
            continue
        clauses = seg.split(":")
        if clauses[0] != "swap":
            continue  # churn / classic fault segment — not ours
        ev: dict = {"at_step": 0, "action": None}
        for clause in clauses[1:]:
            key, eq, val = clause.partition("=")
            if not eq:
                raise ValueError(
                    f"swap spec: clause {clause!r} is not key=value")
            if key == "at_step":
                ev["at_step"] = int(val)
            elif key == "action":
                if val not in ("publish", "corrupt", "die"):
                    raise ValueError(
                        f"swap spec: unknown action {val!r} (want publish, "
                        f"corrupt or die)")
                ev["action"] = val
            else:
                raise ValueError(f"swap spec: unknown key {key!r}")
        if ev["action"] is None:
            raise ValueError(f"swap spec: missing action= clause in {seg!r}")
        events.append(ev)
    return events


# -- parameter <-> wire helpers ----------------------------------------------


def flatten_params(params) -> np.ndarray:
    """Flatten a parameter pytree to ONE C-contiguous f32 vector in
    tree-canonical leaf order — the unit the broadcast ships."""
    import jax

    leaves = jax.tree_util.tree_leaves(params)
    if not leaves:
        return np.zeros(0, np.float32)
    return np.concatenate(
        [np.asarray(leaf, np.float32).ravel() for leaf in leaves])


def unflatten_params(template, flat: np.ndarray):
    """Rebuild a pytree with `template`'s structure/shapes/dtypes from the
    flat f32 vector (the receiver's own tree is the shape authority — the
    wire carries no structure, the HELLO model signature already pinned
    it)."""
    import jax
    import jax.numpy as jnp

    leaves, treedef = jax.tree_util.tree_flatten(template)
    out, off = [], 0
    for leaf in leaves:
        n = int(np.prod(leaf.shape)) if leaf.shape else 1
        if off + n > flat.size:
            raise WeightSwapError(
                _ERR, f"flat parameter vector has {flat.size} elements; "
                f"template needs more (truncated publication?)")
        out.append(jnp.asarray(
            np.asarray(flat[off:off + n]).reshape(leaf.shape), leaf.dtype))
        off += n
    if off != flat.size:
        raise WeightSwapError(
            _ERR, f"flat parameter vector has {flat.size} elements; "
            f"template consumes only {off}")
    return jax.tree_util.tree_unflatten(treedef, out)


def roundtrip_params(params, codec: str = "bf16"):
    """Params as EVERY rank will hold them after a publication under
    `codec`: encode once, decode once, rebuild. The frontend's new
    PrefillEngine must be built from THIS (not the pristine checkpoint) so
    prefill and decode tiers stay bitwise identical — the same contract
    single-version serving already pins."""
    flat = flatten_params(params)
    wire = transport.codec_encode(flat, codec)
    return unflatten_params(
        params, transport.codec_decode(wire, codec, flat.size))


@contextlib.contextmanager
def _bounded_bootstrap(deadline: float):
    """Clamp the rendezvous bootstrap to the REMAINING swap budget.

    The bootstrap's own default (TPUNET_BOOTSTRAP_TIMEOUT_MS, 120s) is
    sized for training jobs where rank 0 may start minutes after its
    peers. A swap rendezvous is the opposite regime: the coordinator
    binds milliseconds after the announce, so a member that hasn't joined
    within the swap deadline is dead (or the attempt was abandoned) — and
    a 120s park here would wedge the SERVING loop of whoever waits, which
    is exactly what a live update must never do. The native layer reads
    the knob per rendezvous, so a scoped env override is race-free within
    one process's serve loop."""
    remaining_ms = max(1, int((deadline - time.monotonic()) * 1e3))
    prev = os.environ.get("TPUNET_BOOTSTRAP_TIMEOUT_MS")
    os.environ["TPUNET_BOOTSTRAP_TIMEOUT_MS"] = str(remaining_ms)
    try:
        yield
    finally:
        if prev is None:
            os.environ.pop("TPUNET_BOOTSTRAP_TIMEOUT_MS", None)
        else:
            os.environ["TPUNET_BOOTSTRAP_TIMEOUT_MS"] = prev


def _ephemeral_coordinator(host: str = "127.0.0.1") -> str:
    """Pick a fresh rendezvous address per swap attempt: bind :0, read the
    port, release it. A retry NEVER reuses the previous attempt's
    coordinator, so a receiver stuck in an abandoned rendezvous cannot
    cross-talk with the new one (it times out on the old address)."""
    s = socket.socket()
    try:
        s.bind((host, 0))
        return f"{host}:{s.getsockname()[1]}"
    finally:
        s.close()


# -- receiver (decode rank) --------------------------------------------------


class WeightReceiver:
    """Pumped receive half of one publication on a decode rank.

    ``pump()`` does ONE bounded unit of work per call — wire the bulk-class
    comm on the first pass, receive one broadcast chunk per later pass,
    digest + all-gather after the last — so the owning serve loop keeps
    draining latency traffic between passes. Returns True once ``staged``
    holds the verified, decoded parameter pytree (the caller flips at its
    next request boundary); raises ``WeightSwapError`` on ANY failure
    (deadline, transport death, digest disagreement) with the comm closed
    and nothing staged — the previous version keeps serving."""

    def __init__(self, ann: proto.SwapAnnounce, template, *,
                 corrupt: bool = False):
        self.ann = ann
        self.version = ann.version
        #: Chaos hook ("swap:...:action=corrupt"): flip one byte of the
        #: received wire before digesting — MUST make every rank refuse.
        self.corrupt = corrupt
        self._template = template
        self._comm: Communicator | None = None
        self._nwire = transport.codec_wire_bytes(ann.codec, ann.nelems)
        self._nchunks = max(
            1, -(-self._nwire // max(1, ann.chunk_bytes)))
        self._parts: list[np.ndarray] = []
        self._next = 0
        self._t_phase = time.monotonic()
        self._deadline = self._t_phase + ann.timeout_ms / 1e3
        self.staged = None
        self.done = False

    def _lap(self) -> int:
        now = time.monotonic()
        us = int((now - self._t_phase) * 1e6)
        self._t_phase = now
        return us

    def abort(self) -> None:
        """Discard everything; the old version keeps serving. Idempotent."""
        if self._comm is not None:
            try:
                self._comm.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
            self._comm = None
        if not self.done:
            self._parts.clear()
            self.staged = None
            telemetry.swap_event("abort")
            self.done = True

    def _fail(self, msg: str, cause: Exception | None = None):
        self.abort()
        # Terminal verdict: snapshot the flight recorder at the raise site
        # (swap deadline / death / digest mismatch — DESIGN.md §6c).
        telemetry.flightrec_dump_verdict("swap_abort")
        err = WeightSwapError(
            _ERR, f"weight swap to version {self.ann.version} aborted: "
            f"{msg} — previous version keeps serving; the publisher "
            f"retries or raises")
        raise err from cause

    def pump(self) -> bool:
        """One bounded unit of receive work; True once staged is ready."""
        if self.done:
            return self.staged is not None
        if time.monotonic() > self._deadline:
            self._fail(f"deadline exceeded (TPUNET_SWAP_TIMEOUT_MS="
                       f"{self.ann.timeout_ms})")
        ann = self.ann
        try:
            if self._comm is None:
                # Bulk-class comm, EXPLICIT exact wire + pinned tree: the
                # broadcast ships pre-encoded bytes, so the comm codec must
                # be the identity regardless of TPUNET_WIRE_DTYPE.
                with _bounded_bootstrap(self._deadline):
                    self._comm = Communicator(
                        ann.coordinator, ann.rank, ann.world,
                        wire_dtype="f32", algo="tree",
                        traffic_class=ann.traffic_class)
                telemetry.swap_observe("announce", self._lap())
                return False
            if self._next < self._nchunks:
                lo = self._next * ann.chunk_bytes
                hi = min(self._nwire, lo + ann.chunk_bytes)
                self._parts.append(self._comm.broadcast(
                    np.zeros(hi - lo, np.uint8), root=0))
                self._next += 1
                if self._next < self._nchunks:
                    return False
                telemetry.swap_observe("broadcast", self._lap())
            wire = (np.concatenate(self._parts) if len(self._parts) != 1
                    else self._parts[0])
            if self.corrupt:
                wire = wire.copy()
                wire[0] ^= 0xFF
            digests = self._comm.all_gather(
                np.array([transport.crc32c(wire)], np.uint32))
            telemetry.swap_observe("verify", self._lap())
            if len({int(d) for d in digests.ravel()}) != 1:
                telemetry.swap_event("mismatch")
                self._fail(
                    "cross-rank CRC32C digest disagreement "
                    f"({[hex(int(d)) for d in digests.ravel()]}) — flip "
                    "refused FLEET-WIDE (every rank computed this same "
                    "verdict locally)")
        except _native.NativeError as e:
            if isinstance(e, WeightSwapError):
                raise
            self._fail(f"transport failure mid-broadcast ({e})", e)
        flat = transport.codec_decode(wire, ann.codec, ann.nelems)
        self.staged = unflatten_params(self._template, flat)
        self.done = True
        comm, self._comm = self._comm, None
        comm.close()
        return True


# -- publisher (frontend) ----------------------------------------------------


class WeightPublisher:
    """Frontend half: announce, broadcast, verify, await flips, install.

    Drives one publication at a time against the owning ``Router``'s live
    rank pool. ``publish()`` blocks until the whole fleet flipped (calling
    `pump` — default ``router.poll`` — between broadcast chunks and while
    awaiting flips, so the latency tier keeps draining), retrying up to
    `retries` times on a typed abort; it retains the encoded wire so
    ``catch_up()`` can re-publish to a rank that rejoins stale after dying
    mid-swap."""

    def __init__(self, router, *, codec: str = "bf16",
                 timeout_ms: int | None = None,
                 chunk_bytes: int | None = None,
                 publish_class: str | None = None,
                 coordinator_host: str = "127.0.0.1"):
        from tpunet.config import Config

        cfg = Config.from_env()
        if codec not in ("f32", "bf16"):
            raise ValueError(
                f"weight wire codec must be f32 or bf16, got {codec!r} "
                f"(int8 KV blocks carry per-block scales; whole-checkpoint "
                f"int8 does not)")
        self.router = router
        self.codec = codec
        self.timeout_ms = int(timeout_ms or cfg.swap_timeout_ms)
        self.chunk_bytes = int(chunk_bytes or cfg.swap_chunk_bytes)
        self.publish_class = publish_class or cfg.publish_class
        self._host = coordinator_host
        self._retained: tuple[int, np.ndarray, int] | None = None
        # Attempt sequence: BEGIN/STATUS frames carry (seq << 32) | version
        # as their req_id, so a LATE aborted-status from an abandoned
        # attempt can never poison the retry that superseded it.
        self._seq = 0
        #: Introspection: the live attempt's phase — None when idle, else
        #: "announce" -> "broadcast" -> "verify" -> "flip". Written by the
        #: publishing thread, safe to READ from anywhere (harnesses use it
        #: to schedule chaos deterministically mid-transfer).
        self.phase: str | None = None
        self.stats = {"publishes": 0, "commits": 0, "aborts": 0,
                      "retries": 0, "catch_ups": 0}

    # -- one attempt ---------------------------------------------------------

    def _settle(self, pump, window_s: float = 0.1) -> None:
        """Pump long enough for the transport engine to surface a dead
        peer's EOF on its tier link (~10ms observed on loopback; the
        window is 10x that) so the next attempt's target set excludes
        ranks that died during the failed one. A single pump() is NOT
        enough: an abort lands milliseconds after the death, before the
        engine has flagged the link, and re-announcing to the corpse
        parks the rendezvous on the bootstrap timeout with the serving
        loop wedged behind it."""
        t_end = time.monotonic() + window_s
        while time.monotonic() < t_end:
            pump()
            time.sleep(0.002)

    def _broadcast_to(self, targets, version: int, token: int,
                      wire: np.ndarray, nelems: int, deadline: float,
                      pump, comm_box: dict | None = None) -> None:
        """Announce + bulk-class tree broadcast + CRC all-gather against
        `targets` (live _Ranks). Raises WeightSwapError on any failure.
        `comm_box`, when given, exposes the live comm under "comm" so a
        supervising thread can force-close it past the deadline."""
        self.phase = "announce"
        t_phase = time.monotonic()
        world = len(targets) + 1
        coord = _ephemeral_coordinator(self._host)
        _dbg(f"announce targets={[r.index for r in targets]} coord={coord} "
             f"version={version}")
        for i, rank in enumerate(targets):
            ann = proto.SwapAnnounce(
                version, world, i + 1, nelems, self.chunk_bytes, self.codec,
                self.timeout_ms, coord, traffic_class=self.publish_class)
            try:
                rank.link.send_frame(proto.T_SWAP_BEGIN, token,
                                     proto.pack_swap_begin(ann))
            except (_native.NativeError, TimeoutError, OSError) as e:
                self.router._fail_rank(rank, e)
                raise WeightSwapError(
                    _ERR, f"swap announce to decode rank {rank.index} "
                    f"failed ({e}) — rank reaped, publication aborted"
                ) from e
        comm = None
        try:
            _dbg("ctor begin")
            with _bounded_bootstrap(deadline):
                comm = Communicator(coord, 0, world, wire_dtype="f32",
                                    algo="tree",
                                    traffic_class=self.publish_class)
            _dbg("ctor done")
            if comm_box is not None:
                comm_box["comm"] = comm
            self.phase = "broadcast"
            telemetry.swap_observe(
                "announce", int((time.monotonic() - t_phase) * 1e6))
            t_phase = time.monotonic()
            nwire = int(wire.size)
            nchunks = max(1, -(-nwire // max(1, self.chunk_bytes)))
            for c in range(nchunks):
                if time.monotonic() > deadline:
                    raise WeightSwapError(
                        _ERR, f"weight broadcast exceeded "
                        f"TPUNET_SWAP_TIMEOUT_MS={self.timeout_ms} at chunk "
                        f"{c}/{nchunks}")
                lo = c * self.chunk_bytes
                comm.broadcast(wire[lo:min(nwire, lo + self.chunk_bytes)],
                               root=0)
                _dbg(f"chunk {c}/{nchunks} sent")
                pump()  # latency tier keeps draining between bulk chunks
            self.phase = "verify"
            telemetry.swap_observe(
                "broadcast", int((time.monotonic() - t_phase) * 1e6))
            t_phase = time.monotonic()
            digests = comm.all_gather(
                np.array([transport.crc32c(wire)], np.uint32))
            telemetry.swap_observe(
                "verify", int((time.monotonic() - t_phase) * 1e6))
            if len({int(d) for d in digests.ravel()}) != 1:
                telemetry.swap_event("mismatch")
                raise WeightSwapError(
                    _ERR, "cross-rank CRC32C digest disagreement "
                    f"({[hex(int(d)) for d in digests.ravel()]}) — flip "
                    "refused FLEET-WIDE; no rank staged these bytes")
        except _native.NativeError as e:
            if isinstance(e, WeightSwapError):
                raise
            raise WeightSwapError(
                _ERR, f"weight broadcast to version {version} failed "
                f"mid-flight ({e}) — receivers abort and keep serving the "
                f"previous version") from e
        finally:
            if comm is not None:
                comm.close()

    def _supervised_cast(self, targets, version: int, token: int,
                         wire: np.ndarray, nelems: int, deadline: float,
                         pump) -> None:
        """Run ``_broadcast_to`` on a background thread while THIS thread
        keeps pumping the serve loop. Past the deadline the live comm is
        force-closed under the thread (a blocking collective then fails
        fast); if the native layer STILL hasn't surfaced an error a grace
        window later — a SIGKILLed peer can wedge a collective beyond
        close()'s reach — the daemon thread is abandoned and the attempt
        raises typed. The abandoned attempt's token is superseded by the
        retry's, so even a zombie that eventually reports cannot poison a
        later attempt."""
        cast_box: dict = {}

        def _run_broadcast() -> None:
            try:
                self._broadcast_to(targets, version, token, wire, nelems,
                                   deadline, pump=lambda: None,
                                   comm_box=cast_box)
                cast_box["ok"] = True
            except BaseException as e:  # noqa: BLE001 — re-raised below
                cast_box["err"] = e

        caster = threading.Thread(
            target=_run_broadcast,
            name=f"tpunet-publish-v{version}", daemon=True)
        caster.start()
        closed = False
        while caster.is_alive():
            now = time.monotonic()
            if now > deadline and not closed:
                # The thread checks the deadline between chunks but can
                # park inside a blocking collective; closing the comm
                # under it fails that op fast.
                comm = cast_box.get("comm")
                if comm is not None:
                    closed = True
                    try:
                        comm.close()
                    except Exception:  # noqa: BLE001 — teardown
                        pass
            if now > deadline + _CAST_ABANDON_GRACE_S:
                _dbg(f"abandoning parked broadcast thread for v{version}")
                raise WeightSwapError(
                    _ERR, f"weight broadcast to version {version} still "
                    f"parked {_CAST_ABANDON_GRACE_S:.0f}s past "
                    f"TPUNET_SWAP_TIMEOUT_MS={self.timeout_ms} with its "
                    f"comm closed — native collective wedged (peer died "
                    f"mid-operation); thread abandoned, attempt aborted")
            pump()
            time.sleep(0.001)
        caster.join()
        if "err" in cast_box:
            raise cast_box["err"]

    def _await_flips(self, targets, version: int, token: int,
                     deadline: float, pump) -> None:
        """Poll the router until every surviving target reported FLIPPED
        for THIS attempt's token. An ABORTED verdict or a fully-dead
        target set raises; a target that dies after the broadcast is
        dropped from the wait (it will be caught up on readmission)."""
        want = {rank.index: rank for rank in targets}
        while True:
            pump()
            status = self.router._swap_status
            aborted = sorted(
                i for i in want if status.get((i, token)) == "aborted")
            if aborted:
                raise WeightSwapError(
                    _ERR, f"decode rank(s) {aborted} aborted the swap to "
                    f"version {version} — flip refused fleet-wide")
            alive = {i for i, rank in want.items() if rank.alive}
            if not alive:
                raise WeightSwapError(
                    _ERR, f"every announced decode rank died during the "
                    f"swap to version {version}")
            if all(status.get((i, token)) == "flipped" for i in alive):
                return
            if time.monotonic() > deadline:
                missing = sorted(
                    i for i in alive
                    if status.get((i, token)) != "flipped")
                raise WeightSwapError(
                    _ERR, f"decode rank(s) {missing} did not flip to "
                    f"version {version} within TPUNET_SWAP_TIMEOUT_MS="
                    f"{self.timeout_ms}")
            time.sleep(0.001)

    # -- public surface ------------------------------------------------------

    def publish(self, version: int, params, *, retries: int = 2,
                pump=None, warm_lengths=()) -> None:
        """Publish checkpoint `version` (a parameter pytree shaped like the
        serving model's) to every live decode rank and install the matching
        bf16-roundtripped PrefillEngine frontend-side. Blocks until the
        fleet flipped; on a typed abort the whole attempt retries (fresh
        coordinator, reaped ranks dropped) up to `retries` times. The old
        version keeps serving throughout and drains under session pinning
        before it retires. `warm_lengths` pre-compiles the new prefill for
        those prompt lengths before it goes live."""
        if version <= self.router.version:
            raise ValueError(
                f"published version must increase: {version} <= current "
                f"{self.router.version}")
        pump = pump or self.router.poll
        flat = flatten_params(params)
        wire = transport.codec_encode(flat, self.codec)
        # THIS thread never stops pumping. Both halves of a publication
        # run on background threads — the bulk transfer (rendezvous +
        # chunk stream + CRC all-gather: each step can block on the
        # slowest receiver, which drains ONE chunk per serve pass) and
        # the frontend engine build + jit warm (XLA compiles release the
        # GIL). In-flight requests never pay the swap in their TTFT —
        # the same bargain the decode flip makes. The builder starts
        # ONCE, outside the retry loop: the engine depends only on the
        # verified bytes, not on which attempt delivered them.
        t_flip = time.monotonic()
        rt = unflatten_params(params, transport.codec_decode(
            wire, self.codec, flat.size))
        old = self.router.prefill
        box: dict = {}

        def _build_and_warm() -> None:
            try:
                engine = PrefillEngine(
                    old.model, rt, max_len=old.max_len,
                    prefill_chunk=getattr(old, "_chunk", None))
                for plen in warm_lengths:
                    engine.prefill(np.zeros(int(plen), np.int32))
                box["engine"] = engine
            except BaseException as e:  # noqa: BLE001 — typed below
                box["err"] = e

        builder = threading.Thread(
            target=_build_and_warm,
            name=f"tpunet-prefill-v{version}", daemon=True)
        builder.start()
        attempt = 0
        while True:
            self.stats["publishes"] += 1
            telemetry.swap_event("publish")
            self._seq += 1
            token = (self._seq << 32) | version
            deadline = time.monotonic() + self.timeout_ms / 1e3
            try:
                targets = [r for r in self.router._ranks if r.alive]
                if not targets:
                    raise WeightSwapError(
                        _ERR, "no live decode rank to publish to")
                self._supervised_cast(targets, version, token, wire,
                                      flat.size, deadline, pump)
                self._await_flips(targets, version, token, deadline, pump)
                self.phase = "flip"
                while builder.is_alive():
                    if time.monotonic() > deadline:
                        raise WeightSwapError(
                            _ERR, f"prefill build/warm for version "
                            f"{version} exceeded TPUNET_SWAP_TIMEOUT_MS="
                            f"{self.timeout_ms}")
                    pump()
                    time.sleep(0.001)
                builder.join()
                if "err" in box:
                    raise WeightSwapError(
                        _ERR, f"prefill build/warm for version {version} "
                        f"failed ({box['err']})") from box["err"]
                self.router.install_version(version, box["engine"])
                telemetry.swap_observe(
                    "flip", int((time.monotonic() - t_flip) * 1e6))
                telemetry.swap_event("commit")
                self.stats["commits"] += 1
                self._retained = (version, wire, int(flat.size))
                self.phase = None
                return
            except WeightSwapError as e:
                _dbg(f"attempt {attempt} failed: {e}")
                self.phase = None
                self.stats["aborts"] += 1
                attempt += 1
                if attempt > retries:
                    # Terminal (retries exhausted): snapshot the flight
                    # recorder at the raise site (DESIGN.md §6c).
                    telemetry.flightrec_dump_verdict("swap_deadline")
                    raise
                telemetry.swap_event("retry")
                self.stats["retries"] += 1
                self._settle(pump)  # reap dead links before re-announcing
                _dbg("post-retry alive="
                     f"{[(r.index, r.alive) for r in self.router._ranks]}")

    def catch_up(self, *, pump=None) -> int:
        """Re-publish the retained current checkpoint to every live rank
        that serves an older version (a host readmitted after dying
        mid-swap announces its stale version in the HELLO). Each stale
        rank gets its own world=2 broadcast of the SAME retained wire —
        byte-identical to what the fleet verified, so the catch-up flip
        passes the same CRC gate. Returns the number of ranks caught up;
        raises WeightSwapError if a catch-up aborts."""
        if self._retained is None:
            return 0
        version, wire, nelems = self._retained
        pump = pump or self.router.poll
        self._settle(pump)  # catch-up usually follows churn: reap first
        caught = 0
        try:
            return self._catch_up_inner(version, wire, nelems, pump, caught)
        finally:
            self.phase = None

    def _catch_up_inner(self, version, wire, nelems, pump,
                        caught: int) -> int:
        for rank in list(self.router._ranks):
            if not rank.alive or version in rank.versions:
                continue
            deadline = time.monotonic() + self.timeout_ms / 1e3
            telemetry.swap_event("publish")
            self._seq += 1
            token = (self._seq << 32) | version
            self._supervised_cast([rank], version, token, wire, nelems,
                                  deadline, pump)
            self._await_flips([rank], version, token, deadline, pump)
            telemetry.swap_event("commit")
            self.stats["catch_ups"] += 1
            caught += 1
        return caught
