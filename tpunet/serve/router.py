"""Frontend tier: request admission, placement, and failure containment.

The Router is the client-facing half of the disaggregated serving tier. It
owns the PrefillEngine and one FrameLink per decode rank, and drives four
concerns:

**Admission + backpressure.** ``submit()`` rejects with RouterBusyError
when every decode slot is occupied AND the admission queue is at its
limit — clients see a typed, retryable signal instead of unbounded queue
growth.

**Placement.** Dispatch picks the decode rank with the most free slots
("least_loaded", default) or cycles ("round_robin" — TPUNET_ROUTER_POLICY).
Prefill runs at dispatch, the KV block is codec-encoded ONCE, and the
encoded frame is what ships.

**Failure containment.** A decode rank that errors or times out is marked
dead; every request in flight on it is re-queued AT THE FRONT and replayed
on a surviving rank — from the RETAINED encoded KV block when
``retain_kv=True`` (the default: no second prefill), else by re-prefilling
from the prompt. Results are only ever released as complete token arrays,
so a mid-request rank death can delay a response but never corrupt or
truncate it; with greedy sampling the replayed stream is bitwise the one
the dead rank would have produced.

**Re-admission.** Death is no longer permanent: with
``enable_readmission(listen_sock)`` the router keeps probing its wiring
port (every ``TPUNET_READMIT_PROBE_MS``) for recovered decode hosts. A
rejoining rank runs the FULL hello re-handshake — a config-signature or
codec drift on rejoin fails typed (``TierMismatchError`` /
``KVCodecMismatchError``) instead of silently re-admitting a host that
would serve a different model — and on success re-enters the placement
pool as a fresh rank (``tpunet_churn_events_total{kind="readmit"}``),
immediately eligible for dispatch. Replay-from-retained-KV composes
unchanged: a stream stranded by the death completes on survivors (or on
the readmitted rank itself) with zero truncation either way.

**SLO observability.** TTFT is stamped when a rank's FIRST frame arrives
(admission -> first token, the client-perceived number) into
``tpunet_req_ttft_us``; the decode-measured TPOT rides each RESULT frame
into ``tpunet_req_tpot_us``; router/prefill queue depths export through
``tpunet_serve_queue_depth`` — all over the existing metrics/scrape
pipeline.
"""

from __future__ import annotations

import socket
import time
from collections import deque

import numpy as np

from tpunet import _native, telemetry, transport
from tpunet.serve import kv as kv_mod
from tpunet.serve import protocol as proto
from tpunet.serve.prefill import PrefillEngine

POLICIES = ("least_loaded", "round_robin")


class _Rank:
    def __init__(self, link: proto.FrameLink, index: int):
        self.link = link
        self.index = index
        self.slots = max(1, link.peer.slots)
        self.inflight: set[int] = set()
        self.alive = True
        # Checkpoint versions resident on this rank: seeded from the HELLO
        # (a readmitted host announces the version it still serves — stale
        # is LEGAL, the publisher catches it up; a pre-swap peer without
        # the field is version 0), grown by SWAP_STATUS flips, shrunk by
        # the retire sweep.
        self.versions: set[int] = {getattr(link.peer, "weight_version", 0)}

    def free(self) -> int:
        return self.slots - len(self.inflight)


class Router:
    """Admission + placement + failover frontend over N decode ranks."""

    def __init__(self, prefill: PrefillEngine, *, kv_codec: str | None = None,
                 policy: str | None = None, queue_limit: int | None = None,
                 retain_kv: bool = True, net: transport.Net | None = None):
        from tpunet.config import Config

        cfg = Config.from_env()
        kv_codec = kv_codec or cfg.kv_wire_dtype
        policy = policy or cfg.router_policy
        if kv_codec not in kv_mod.KV_CODECS:
            raise ValueError(f"unknown KV wire codec {kv_codec!r}")
        if policy not in POLICIES:
            raise ValueError(
                f"router policy must be one of {POLICIES}, got {policy!r}")
        self.prefill = prefill
        self.kv_codec = kv_codec
        self.policy = policy
        self.retain_kv = retain_kv
        self._queue_limit = queue_limit
        # Live weight updates (docs/DESIGN.md "Live weight updates"): the
        # version NEW sessions are admitted under, one PrefillEngine per
        # still-draining version (a request prefilled under v1 must decode
        # and REPLAY under v1 — bitwise pinning), swap verdicts keyed by
        # (rank index, attempt token), and versions awaiting drain-retire.
        self.version = 0
        self._prefills: dict[int, PrefillEngine] = {0: prefill}
        self._swap_status: dict[tuple[int, int], str] = {}
        self._retire_pending: set[int] = set()
        # KV BLOCK and FIRST/RESULT frames ship on a LATENCY-class link:
        # the class nibble rides every comm this Net wires, so TTFT-bound
        # tier traffic never queues behind a co-tenant's bulk gradient
        # AllReduce in the QoS scheduler (docs/DESIGN.md "Transport QoS").
        self._net = net or transport.Net(traffic_class="latency")
        self._ranks: list[_Rank] = []
        self._rr_next = 0
        self._queue: deque[dict] = deque()
        self._recs: dict[int, dict] = {}
        self._results: dict[int, np.ndarray] = {}
        self._next_id = 0
        # Re-admission probing (docs/DESIGN.md "Elastic churn"): armed by
        # enable_readmission(); run() polls the wiring port at this cadence.
        self._listen_sock: socket.socket | None = None
        self._probe_interval = max(1, cfg.readmit_probe_ms) / 1e3
        self._last_probe = 0.0
        self.stats = {"submitted": 0, "completed": 0, "rank_failures": 0,
                      "replays_kv": 0, "replays_prefill": 0, "rejected": 0,
                      "qos_backpressure": 0, "readmissions": 0,
                      "readmit_rejected": 0, "swaps": 0, "swap_aborts": 0}

    # -- wiring ------------------------------------------------------------

    @staticmethod
    def listen(addr: str = "127.0.0.1:0") -> socket.socket:
        """Bind the tier wiring port; returns the listening socket (query
        ``.getsockname()`` for the chosen port when addr ends in :0)."""
        host, _, port = addr.rpartition(":")
        sock = socket.socket()
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((host or "127.0.0.1", int(port)))
        sock.listen(16)
        return sock

    def _hello(self) -> proto.Hello:
        return proto.Hello(proto.ROLE_FRONTEND, self.kv_codec, 0,
                           self.prefill.max_len, self.prefill.model.vocab,
                           kv_mod.model_signature(self.prefill.model),
                           weight_version=self.version)

    def accept_ranks(self, listen_sock: socket.socket, n: int,
                     timeout: float = 60.0) -> None:
        """Accept `n` decode ranks on the wiring socket, running the hello
        handshake (typed mismatch on every rank) and comm bring-up for
        each."""
        listen_sock.settimeout(timeout)
        for _ in range(n):
            conn, _ = listen_sock.accept()
            try:
                link = proto.wire_frontend(
                    conn, self._net, self._hello(),
                    name=f"decode-{len(self._ranks)}")
            finally:
                conn.close()
            self._ranks.append(_Rank(link, len(self._ranks)))

    # -- re-admission ------------------------------------------------------

    def enable_readmission(self, listen_sock: socket.socket) -> None:
        """Keep the wiring port open for recovered decode hosts: run()
        (and explicit poll_admissions() calls) will accept reconnects,
        re-run the hello handshake, and re-enter survivors of a rank
        failure into the placement pool. The socket stays caller-owned."""
        listen_sock.setblocking(False)
        self._listen_sock = listen_sock

    def poll_admissions(self, raise_on_mismatch: bool = True) -> int:
        """Non-blocking accept pass over the wiring port (the router-side
        health probe: a recovered host proves liveness by reconnecting).
        Each pending connection runs the FULL hello re-handshake; a
        config-signature/codec drift is a typed TierMismatchError —
        re-raised when `raise_on_mismatch` (the unit-test/operator surface),
        else counted in stats["readmit_rejected"] and contained (the
        serving loop must not die because a stale host knocked). Returns
        the number of ranks re-admitted."""
        if self._listen_sock is None:
            return 0
        admitted = 0
        while True:
            try:
                conn, _ = self._listen_sock.accept()
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                break  # listener closed under us — probing just stops
            try:
                conn.setblocking(True)
                link = proto.wire_frontend(
                    conn, self._net, self._hello(),
                    name=f"decode-{len(self._ranks)}")
            except proto.TierMismatchError:
                self.stats["readmit_rejected"] += 1
                if raise_on_mismatch:
                    raise
                continue
            except (proto.ServeError, _native.NativeError, OSError):
                # Half-open reconnect (the host died again mid-handshake):
                # not a pool event, just drop the carcass.
                continue
            finally:
                conn.close()
            self._ranks.append(_Rank(link, len(self._ranks)))
            self.stats["readmissions"] += 1
            telemetry.churn_event("readmit")
            admitted += 1
        if admitted:
            self._pump()  # queued work flows onto the recovered capacity
        return admitted

    # -- admission ---------------------------------------------------------

    def _capacity(self) -> int:
        return sum(r.slots for r in self._ranks if r.alive)

    def submit(self, prompt, max_new_tokens: int) -> int:
        """Admit one request; returns its id. Raises RouterBusyError when
        every decode slot is occupied and the queue is at its limit."""
        limit = (self._queue_limit if self._queue_limit is not None
                 else 2 * max(1, self._capacity()))
        free = sum(r.free() for r in self._ranks if r.alive)
        if free <= 0 and len(self._queue) >= limit:
            self.stats["rejected"] += 1
            raise proto.RouterBusyError(
                f"all decode slots busy and admission queue at its limit "
                f"({limit}); retry later")
        prompt = np.asarray(prompt, np.int32)
        rid = self._next_id
        self._next_id += 1
        rec = {"id": rid, "prompt": prompt, "max_new": int(max_new_tokens),
               "payload": None, "t_submit": time.monotonic(),
               "t_first": None, "rank": None,
               # Pinned at admission: this request prefills, decodes, and
               # REPLAYS under the version current right now, even if a
               # swap lands while it is in flight (bitwise session
               # stability across publications).
               "version": self.version}
        self._recs[rid] = rec
        self._queue.append(rec)
        self.stats["submitted"] += 1
        self._gauges()
        self._pump()
        return rid

    def _gauges(self) -> None:
        telemetry.serve_queue_depth("router", len(self._queue))
        telemetry.serve_queue_depth(
            "prefill", sum(1 for r in self._queue if r["payload"] is None))

    # -- placement + dispatch ----------------------------------------------

    def _pick_rank(self, version: int | None = None) -> _Rank | None:
        live = [r for r in self._ranks if r.alive and r.free() > 0]
        if not live:
            return None
        if version is not None:
            # Version-pinned placement: prefer ranks where the request's
            # version is resident (mixed-version pools mid-swap / a stale
            # readmitted host). Fall through to the whole pool only when
            # nobody holds it — the decode side then serves on current,
            # its never-drop belt.
            resident = [r for r in live if version in r.versions]
            if resident:
                live = resident
        if self.policy == "round_robin":
            live.sort(key=lambda r: (r.index < self._rr_next, r.index))
            rank = live[0]
            self._rr_next = rank.index + 1
            return rank
        return max(live, key=lambda r: r.free())  # least loaded

    def _build_payload(self, rec: dict) -> bytes:
        # Prefill under the request's PINNED version (the engine for a
        # draining version stays resident until retire).
        eng = self._prefills.get(rec.get("version", self.version),
                                 self.prefill)
        kv_rows, logits = eng.prefill(rec["prompt"])
        wire = kv_mod.encode_kv_block(kv_rows, self.kv_codec)
        n_kv = kv_mod.kv_block_elems(
            eng.kv_leaf_shapes(len(rec["prompt"])))
        return proto.pack_block(rec["prompt"], rec["max_new"], wire, n_kv,
                                logits, self.kv_codec)

    def _pump(self) -> None:
        """Dispatch queued requests while live capacity exists."""
        while self._queue:
            rank = self._pick_rank(self._queue[0].get("version"))
            if rank is None:
                if not any(r.alive for r in self._ranks):
                    if self._listen_sock is not None:
                        break  # re-admission armed: wait for a rejoin
                    raise proto.NoLiveDecodeRankError(
                        "every decode rank has failed; "
                        f"{len(self._queue)} request(s) cannot be placed")
                break  # saturated: wait for retirements
            rec = self._queue.popleft()
            payload = rec["payload"]
            if payload is None:
                payload = self._build_payload(rec)
                if self.retain_kv:
                    # Keep the ENCODED block for replay-from-KV: a decode
                    # death re-ships these bytes instead of re-prefilling.
                    rec["payload"] = payload
            try:
                rank.link.send_frame(proto.T_BLOCK, rec["id"], payload,
                                     aux=rec.get("version", self.version))
            except _native.QosAdmissionError:
                # Typed QoS backpressure: the latency class's in-flight
                # budget is full. NOTHING reached the wire (the header send
                # is the admission point), so requeue front-of-queue and
                # retry on the next poll — the rank is healthy.
                self.stats["qos_backpressure"] += 1
                self._queue.appendleft(rec)
                break
            except (_native.NativeError, TimeoutError, OSError) as e:
                self._queue.appendleft(rec)
                self._fail_rank(rank, e)
                continue
            rec["rank"] = rank.index
            rank.inflight.add(rec["id"])
        self._gauges()

    # -- completion + failover ---------------------------------------------

    def _fail_rank(self, rank: _Rank, exc: Exception) -> None:
        """Contain a decode-rank failure: mark it dead and replay every
        request it held — from the retained KV block when present (no
        second prefill), else by re-prefilling from the prompt. Requeued at
        the FRONT so stranded requests don't also pay the whole queue
        again."""
        if not rank.alive:
            return
        rank.alive = False
        self.stats["rank_failures"] += 1
        rank.link.close()
        for rid in sorted(rank.inflight, reverse=True):
            if rid in self._results:
                continue  # completed before the rank died
            rec = self._recs[rid]
            rec["rank"] = None
            if rec["payload"] is not None:
                self.stats["replays_kv"] += 1
            else:
                self.stats["replays_prefill"] += 1
            self._queue.appendleft(rec)
        rank.inflight.clear()
        self._gauges()

    def poll(self) -> None:
        """Drain every live rank's frames; contain failures."""
        for rank in self._ranks:
            if not rank.alive:
                continue
            while True:
                try:
                    frame = rank.link.poll()
                except (_native.NativeError, proto.KVIntegrityError,
                        proto.TierProtocolError, OSError) as e:
                    # Transport death, a corrupt frame, or protocol garbage:
                    # the rank is no longer trustworthy — replay its work.
                    self._fail_rank(rank, e)
                    break
                if frame is None:
                    break
                ftype, rid, payload, aux = frame
                if ftype == proto.T_SWAP_STATUS:
                    # rid is the publisher's attempt token
                    # ((seq << 32) | version) — echoing it back makes a
                    # LATE aborted-status from an abandoned attempt inert.
                    version = rid & 0xFFFFFFFF
                    if aux == proto.SWAP_FLIPPED:
                        rank.versions.add(version)
                        self._swap_status[(rank.index, rid)] = "flipped"
                        self.stats["swaps"] += 1
                    else:
                        self._swap_status[(rank.index, rid)] = "aborted"
                        self.stats["swap_aborts"] += 1
                    continue
                rec = self._recs.get(rid)
                if rec is None or rid in self._results:
                    continue  # duplicate after a replay — drop
                if ftype == proto.T_FIRST:
                    if rec["t_first"] is None:
                        rec["t_first"] = time.monotonic()
                        telemetry.serve_observe(
                            "ttft",
                            int((rec["t_first"] - rec["t_submit"]) * 1e6))
                elif ftype == proto.T_RESULT:
                    tokens, status, tpot_us = proto.unpack_result(payload)
                    if status != 0:
                        self._fail_rank(
                            rank,
                            proto.ServeError(f"decode status {status}"))
                        break
                    self._results[rid] = np.asarray(tokens, np.int32)
                    rec["payload"] = None  # replay retention no longer needed
                    rank.inflight.discard(rid)
                    self.stats["completed"] += 1
                    if tpot_us > 0:
                        telemetry.serve_observe("tpot", tpot_us)
        self._retire_sweep()
        self._pump()

    # -- live weight updates -------------------------------------------------

    def install_version(self, version: int, engine: PrefillEngine) -> None:
        """Adopt `engine` as the prefill for checkpoint `version` and make
        it current for NEW sessions. The previous version's engine stays
        resident for its pinned in-flight sessions and retires only once
        they drain (docs/DESIGN.md "Live weight updates"); called by
        WeightPublisher after the fleet flipped."""
        old = self.version
        self._prefills[version] = engine
        self.prefill = engine
        self.version = version
        telemetry.weight_version(version)
        if old != version:
            self._retire_pending.add(old)

    def _retire_sweep(self) -> None:
        """Retire drained versions: once NO admitted request still pins an
        old version, tell every rank holding it to drop it after its own
        local drain, and drop the frontend engine."""
        for ver in list(self._retire_pending):
            if ver == self.version:
                self._retire_pending.discard(ver)
                continue
            if any(rec.get("version") == ver and rec["id"] not in
                   self._results for rec in self._recs.values()):
                continue  # version still has in-flight pinned sessions
            for rank in self._ranks:
                if rank.alive and ver in rank.versions:
                    try:
                        rank.link.send_frame(proto.T_SWAP_RETIRE, ver,
                                             aux=ver)
                    except Exception:  # noqa: BLE001 — failure poll reaps
                        pass
                rank.versions.discard(ver)
            self._prefills.pop(ver, None)
            self._retire_pending.discard(ver)

    # -- driving -----------------------------------------------------------

    def outstanding(self) -> int:
        return len(self._recs) - len(self._results)

    def run(self, timeout: float = 300.0,
            poll_interval: float = 0.001) -> dict[int, np.ndarray]:
        """Drive until every admitted request has a result (or raise on
        timeout / total rank loss); returns {request_id: tokens} for every
        request admitted since the last run() and clears the slate."""
        deadline = time.monotonic() + timeout
        while self.outstanding() > 0:
            now = time.monotonic()
            if (self._listen_sock is not None
                    and now - self._last_probe >= self._probe_interval):
                self._last_probe = now
                # Contain drift rejections here: the serving loop keeps
                # draining; poll_admissions() raises only when called
                # directly (the operator/unit-test surface).
                self.poll_admissions(raise_on_mismatch=False)
            self.poll()
            if self.outstanding() == 0:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"{self.outstanding()} request(s) unfinished after "
                    f"{timeout}s")
            time.sleep(poll_interval)
        results, self._results = self._results, {}
        self._recs.clear()
        self._gauges()
        return results

    def shutdown(self) -> None:
        """Ask every live decode rank to drain and exit (best effort)."""
        for rank in self._ranks:
            if not rank.alive:
                continue
            try:
                rank.link.send_frame(proto.T_SHUTDOWN, 0, timeout=5.0)
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    def close(self) -> None:
        for rank in self._ranks:
            rank.link.close()
        self._net.close()
