"""Decode tier: the BatchServer slot machine fed by shipped KV blocks.

A DecodeWorker owns one BatchServer and one FrameLink to the frontend. Its
serve loop is single-threaded and non-blocking: drain arriving BLOCK
frames (decode the KV wire, ``submit_kv`` — never a re-prefill), advance
every live slot one window, then report — a FIRST frame the moment a
request's first token commits (the router's TTFT stamp) and a RESULT frame
with the full token array and the measured TPOT when it retires. Requests
are never streamed token-by-token across the DCN: a request either
completes with its whole (exact) output or it doesn't report at all and
the router replays it elsewhere — the invariant that makes decode-rank
death unable to corrupt or truncate a stream.
"""

from __future__ import annotations

import time

from tpunet import telemetry, transport
from tpunet.models.serve import BatchServer
from tpunet.serve import kv as kv_mod
from tpunet.serve import protocol as proto


class DecodeWorker:
    """Serve loop around a BatchServer for one decode rank."""

    def __init__(self, model, params, link: proto.FrameLink, *,
                 slots: int, max_len: int, kv_codec: str = "int8",
                 **server_kwargs):
        if kv_codec not in kv_mod.KV_CODECS:
            raise ValueError(f"unknown KV wire codec {kv_codec!r}")
        self._net = None  # set by connect(): the engine this worker owns
        self.link = link
        self.kv_codec = kv_codec
        self.srv = BatchServer(model, params, slots=slots, max_len=max_len,
                               on_first_token=self._on_first,
                               **server_kwargs)
        self._router_id: dict[int, int] = {}  # local id -> router req id
        self._t_first: dict[int, float] = {}
        self._first_pending: list[int] = []
        self.stats = {"blocks": 0, "results": 0}

    def _on_first(self, local_id: int) -> None:
        self._t_first[local_id] = time.monotonic()
        self._first_pending.append(local_id)

    def _ingest(self) -> tuple[bool, bool]:
        """Drain available frames; returns (progressed, shutdown_seen)."""
        progressed = shutdown = False
        while True:
            frame = self.link.poll()
            if frame is None:
                return progressed, shutdown
            progressed = True
            ftype, rid, payload, _aux = frame
            if ftype == proto.T_BLOCK:
                prompt, max_new, n_kv, logits, wire = proto.unpack_block(
                    payload, self.kv_codec)
                shapes = self.srv.kv_leaf_shapes(len(prompt))
                if kv_mod.kv_block_elems(shapes) != n_kv:
                    raise proto.TierProtocolError(
                        f"BLOCK for request {rid} carries {n_kv} KV "
                        f"elements; this model/prompt-length expects "
                        f"{kv_mod.kv_block_elems(shapes)}")
                rows = kv_mod.decode_kv_block(wire, self.kv_codec, shapes)
                local = self.srv.submit_kv(prompt, max_new, rows, logits)
                self._router_id[local] = rid
                self.stats["blocks"] += 1
            elif ftype == proto.T_SHUTDOWN:
                shutdown = True
            else:
                raise proto.TierProtocolError(
                    f"decode tier got unexpected frame type {ftype}")

    def _report(self, finished: list[dict]) -> None:
        # FIRST frames go out before any RESULT so the router's TTFT stamp
        # for a request always precedes its completion.
        for local in self._first_pending:
            rid = self._router_id.get(local)
            if rid is not None:
                self.link.send_frame(proto.T_FIRST, rid)
        self._first_pending.clear()
        for rec in finished:
            rid = self._router_id.pop(rec["id"], None)
            if rid is None:
                continue
            t_first = self._t_first.pop(rec["id"], None)
            ntok = len(rec["tokens"])
            tpot_us = 0
            if t_first is not None and ntok > 1:
                tpot_us = int((time.monotonic() - t_first) / (ntok - 1) * 1e6)
            self.link.send_frame(
                proto.T_RESULT, rid,
                proto.pack_result(rec["tokens"], 0, tpot_us))
            self.stats["results"] += 1

    def serve(self, *, idle_timeout: float | None = None,
              poll_interval: float = 0.001,
              max_blocks: int | None = None) -> None:
        """Run until a SHUTDOWN frame arrives and every live request has
        reported (or `idle_timeout` seconds pass with no traffic — a test
        harness convenience). `max_blocks` returns after ingesting that
        many KV blocks WITHOUT draining — a canary/chaos control (the
        failover tests use it to die with requests in flight). Transport
        errors propagate: a dead frontend ends the worker, and a worker
        killed by fault injection simply stops reporting — the router's
        failover owns what happens next."""
        draining = False
        idle_since = time.monotonic()
        while True:
            progressed, shutdown = self._ingest()
            draining = draining or shutdown
            if max_blocks is not None and self.stats["blocks"] >= max_blocks:
                return
            if self.srv._live or self.srv._pending:
                finished = self.srv.step()
                self._report(finished)
                progressed = True
            telemetry.serve_queue_depth(
                "decode", len(self.srv._live) + len(self.srv._pending))
            if draining and not (self.srv._live or self.srv._pending):
                return
            if progressed:
                idle_since = time.monotonic()
            else:
                if (idle_timeout is not None
                        and time.monotonic() - idle_since > idle_timeout):
                    return
                time.sleep(poll_interval)

    def close(self) -> None:
        """Tear down the link (and the engine, when this worker owns one —
        the connect() path): comms closed, stream threads joined."""
        self.link.close()
        if self._net is not None:
            self._net.close()
            self._net = None


def connect(addr, model, params, *, slots: int, max_len: int,
            kv_codec: str | None = None, timeout: float = 60.0,
            net: transport.Net | None = None,
            **server_kwargs) -> DecodeWorker:
    """Wire this process to a frontend at `addr` ("host:port" or tuple) as
    a decode rank and return the ready DecodeWorker. `kv_codec` None
    defers to TPUNET_KV_WIRE_DTYPE (default int8)."""
    from tpunet.config import Config

    if kv_codec is None:
        kv_codec = Config.from_env().kv_wire_dtype
    owns_net = net is None
    # Latency-class link: FIRST/RESULT frames are the router's TTFT signal
    # (see Router.__init__ on why the tier rides the latency lane).
    net = net or transport.Net(traffic_class="latency")
    hello = proto.Hello(proto.ROLE_DECODE, kv_codec, slots, max_len,
                        model.vocab, kv_mod.model_signature(model))
    link = proto.wire_decode(addr, net, hello, timeout=timeout)
    worker = DecodeWorker(model, params, link, slots=slots, max_len=max_len,
                          kv_codec=kv_codec, **server_kwargs)
    if owns_net:
        worker._net = net  # close() tears the engine down with the link
    return worker
