"""Decode tier: the BatchServer slot machine fed by shipped KV blocks.

A DecodeWorker owns one BatchServer PER RESIDENT CHECKPOINT VERSION and
one FrameLink to the frontend. Its serve loop is single-threaded and
non-blocking: drain arriving BLOCK frames (decode the KV wire,
``submit_kv`` — never a re-prefill), advance every live slot one window,
then report — a FIRST frame the moment a request's first token commits
(the router's TTFT stamp) and a RESULT frame with the full token array
and the measured TPOT when it retires. Requests are never streamed
token-by-token across the DCN: a request either completes with its whole
(exact) output or it doesn't report at all and the router replays it
elsewhere — the invariant that makes decode-rank death unable to corrupt
or truncate a stream.

**Live weight updates** (docs/DESIGN.md "Live weight updates") ride the
same loop: a T_SWAP_BEGIN frame arms a ``WeightReceiver`` that is pumped
ONE bounded unit per pass (the bulk-class broadcast never parks latency
traffic); once the received bytes pass the fleet-wide CRC gate, the new
BatchServer is built AND jit-warmed on a background thread while the old
version keeps serving, and the flip lands between loop iterations — a
request boundary by construction. Each in-flight request stays pinned to
the version that prefilled it (the T_BLOCK aux word); old versions serve
their pinned sessions until the frontend's T_SWAP_RETIRE and the local
drain both agree they're done. Any swap failure raises the typed
``WeightSwapError`` path internally, reports SWAP_ABORTED, and the
previous version keeps serving — never a hang, never a half-flip.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from functools import partial

import numpy as np

from tpunet import telemetry, transport
from tpunet.models.serve import BatchServer
from tpunet.serve import kv as kv_mod
from tpunet.serve import protocol as proto
from tpunet.serve import publish as publish_mod
from tpunet.serve.publish import WeightReceiver, WeightSwapError


class DecodeWorker:
    """Serve loop around per-version BatchServers for one decode rank."""

    def __init__(self, model, params, link: proto.FrameLink, *,
                 slots: int, max_len: int, kv_codec: str = "int8",
                 weight_version: int = 0, **server_kwargs):
        if kv_codec not in kv_mod.KV_CODECS:
            raise ValueError(f"unknown KV wire codec {kv_codec!r}")
        self._net = None  # set by connect(): the engine this worker owns
        self.link = link
        self.kv_codec = kv_codec
        self._model = model
        self._slots = slots
        self._max_len = max_len
        self._server_kwargs = server_kwargs
        self.version = int(weight_version)
        self._params = {self.version: params}
        self._servers = {
            self.version: self._build_server(self.version, params)}
        # (version, local id) -> router req id: BatchServer local ids
        # restart at 0 per instance, so the version is part of the key.
        self._router_id: dict[tuple[int, int], int] = {}
        self._t_first: dict[tuple[int, int], float] = {}
        self._first_pending: list[tuple[int, int]] = []
        # Live-swap state: the pumped receiver, the background build/warm
        # of the next server, versions the frontend says may retire, and
        # the scripted-chaos step counter.
        self._receiver: WeightReceiver | None = None
        self._receiver_token = 0
        self._flip = None  # (version, token, thread, result box, t0)
        self._retiring: set[int] = set()
        self._corrupt_next = False
        self._swap_step = 0
        self.stats = {"blocks": 0, "results": 0, "swaps": 0,
                      "swap_aborts": 0}
        telemetry.weight_version(self.version)

    @property
    def srv(self) -> BatchServer:
        """The CURRENT version's server (compat surface — pinned traffic
        may still be running on older resident versions)."""
        return self._servers[self.version]

    def _build_server(self, version: int, params) -> BatchServer:
        return BatchServer(self._model, params, slots=self._slots,
                           max_len=self._max_len,
                           on_first_token=partial(self._on_first, version),
                           **self._server_kwargs)

    def _on_first(self, version: int, local_id: int) -> None:
        self._t_first[(version, local_id)] = time.monotonic()
        self._first_pending.append((version, local_id))

    # -- frame ingestion -----------------------------------------------------

    def _ingest(self) -> tuple[bool, bool]:
        """Drain available frames; returns (progressed, shutdown_seen)."""
        progressed = shutdown = False
        while True:
            frame = self.link.poll()
            if frame is None:
                return progressed, shutdown
            progressed = True
            ftype, rid, payload, aux = frame
            if ftype == proto.T_BLOCK:
                prompt, max_new, n_kv, logits, wire = proto.unpack_block(
                    payload, self.kv_codec)
                # aux pins the request to the version that prefilled it;
                # fall back to current if that version already retired
                # here (the router only replays onto resident versions in
                # practice — this is the never-drop belt).
                ver = aux if aux in self._servers else self.version
                srv = self._servers[ver]
                shapes = srv.kv_leaf_shapes(len(prompt))
                if kv_mod.kv_block_elems(shapes) != n_kv:
                    raise proto.TierProtocolError(
                        f"BLOCK for request {rid} carries {n_kv} KV "
                        f"elements; this model/prompt-length expects "
                        f"{kv_mod.kv_block_elems(shapes)}")
                rows = kv_mod.decode_kv_block(wire, self.kv_codec, shapes)
                local = srv.submit_kv(prompt, max_new, rows, logits)
                self._router_id[(ver, local)] = rid
                self.stats["blocks"] += 1
            elif ftype == proto.T_SWAP_BEGIN:
                self._begin_swap(rid, payload)
            elif ftype == proto.T_SWAP_RETIRE:
                self._retiring.add(aux)
            elif ftype == proto.T_SHUTDOWN:
                shutdown = True
            else:
                raise proto.TierProtocolError(
                    f"decode tier got unexpected frame type {ftype}")

    def _report(self, finished_by_ver: list[tuple[int, list[dict]]]) -> None:
        # FIRST frames go out before any RESULT so the router's TTFT stamp
        # for a request always precedes its completion.
        for key in self._first_pending:
            rid = self._router_id.get(key)
            if rid is not None:
                self.link.send_frame(proto.T_FIRST, rid)
        self._first_pending.clear()
        for ver, finished in finished_by_ver:
            for rec in finished:
                rid = self._router_id.pop((ver, rec["id"]), None)
                if rid is None:
                    continue  # warmup dummy or already-replayed request
                t_first = self._t_first.pop((ver, rec["id"]), None)
                ntok = len(rec["tokens"])
                tpot_us = 0
                if t_first is not None and ntok > 1:
                    tpot_us = int(
                        (time.monotonic() - t_first) / (ntok - 1) * 1e6)
                self.link.send_frame(
                    proto.T_RESULT, rid,
                    proto.pack_result(rec["tokens"], 0, tpot_us))
                self.stats["results"] += 1

    # -- live weight updates -------------------------------------------------

    def _begin_swap(self, token: int, payload: bytes) -> None:
        ann = proto.unpack_swap_begin(payload)
        if self._receiver is not None:
            # A retry superseded the in-flight attempt: drop it silently
            # (the publisher already abandoned its token — an ABORTED
            # status would be noise it must ignore anyway).
            self._receiver.abort()
            self.stats["swap_aborts"] += 1
        self._receiver = WeightReceiver(
            ann, self._params[self.version], corrupt=self._corrupt_next)
        self._receiver_token = token
        self._corrupt_next = False

    def _status(self, token: int, verdict: int) -> None:
        try:
            self.link.send_frame(proto.T_SWAP_STATUS, token, aux=verdict)
        except Exception:  # noqa: BLE001 — a dead frontend ends us anyway
            pass

    def _pump_swap(self) -> bool:
        """One bounded unit of swap work per loop pass. Never raises: a
        failed swap reports ABORTED and the old version keeps serving."""
        progressed = False
        if self._receiver is not None:
            recv, token = self._receiver, self._receiver_token
            try:
                ready = recv.pump()
            except WeightSwapError:
                self._receiver = None
                self.stats["swap_aborts"] += 1
                self._status(token, proto.SWAP_ABORTED)
                return True
            progressed = True
            if ready:
                # Verified bytes staged: build + jit-warm the new server
                # on a background thread so the old version keeps serving
                # through the compile. The flip itself lands in
                # _pump_swap on a later pass — a request boundary.
                self._receiver = None
                box: dict = {}
                thread = threading.Thread(
                    target=self._build_and_warm,
                    args=(recv.version, recv.staged, box),
                    name=f"tpunet-flip-v{recv.version}", daemon=True)
                thread.start()
                self._flip = (recv.version, token, thread, box,
                              time.monotonic())
        if self._flip is not None and not self._flip[2].is_alive():
            version, token, thread, box, t0 = self._flip
            thread.join()
            self._flip = None
            progressed = True
            if "err" in box:
                self.stats["swap_aborts"] += 1
                telemetry.swap_event("abort")
                self._status(token, proto.SWAP_ABORTED)
            else:
                self._servers[version] = box["srv"]
                self._params[version] = box["params"]
                self.version = version
                telemetry.weight_version(version)
                telemetry.swap_observe(
                    "flip", int((time.monotonic() - t0) * 1e6))
                telemetry.swap_event("commit")
                self.stats["swaps"] += 1
                self._status(token, proto.SWAP_FLIPPED)
        return progressed

    def _build_and_warm(self, version: int, params, box: dict) -> None:
        """Background thread: build the next version's BatchServer and
        drive one throwaway request through it so the adopt/decode jit
        paths are compiled BEFORE the flip — the serving loop never pays
        the compile."""
        try:
            srv = self._build_server(version, params)
            plen = 1
            rows = [np.zeros(s, np.float32)
                    for s in srv.kv_leaf_shapes(plen)]
            logits = np.zeros(self._model.vocab, np.float32)
            srv.submit_kv(np.zeros(plen, np.int32), 4, rows, logits)
            while srv._live or srv._pending:
                srv.step()  # finished dummy has no router id — dropped
            box["srv"] = srv
            box["params"] = params
        except BaseException as e:  # noqa: BLE001 — surfaced as ABORTED
            box["err"] = e

    def _poll_chaos(self) -> None:
        """Scripted swap chaos (swap:at_step=N:action=..., fault.cc): the
        decode side answers "die" (SIGKILL mid-swap — the router replays,
        the publisher aborts and retries) and "corrupt" (flip a received
        byte — the CRC gate must refuse fleet-wide). "publish" verdicts
        belong to the frontend and are ignored here."""
        self._swap_step += 1
        action = publish_mod.swap_action(self._swap_step)
        if action == "die":
            os.kill(os.getpid(), signal.SIGKILL)
        elif action == "corrupt":
            if self._receiver is not None and not self._receiver.done:
                self._receiver.corrupt = True
            else:
                self._corrupt_next = True

    def _retire_drained(self) -> None:
        """Drop retired versions once BOTH the frontend said retire AND no
        local request is still pinned to them."""
        for ver in list(self._retiring):
            if ver == self.version:
                self._retiring.discard(ver)  # never retire the live one
                continue
            srv = self._servers.get(ver)
            if srv is None:
                self._retiring.discard(ver)
                continue
            if (srv._live or srv._pending
                    or any(k[0] == ver for k in self._router_id)):
                continue  # still draining its pinned sessions
            self._servers.pop(ver)
            self._params.pop(ver, None)
            self._retiring.discard(ver)

    # -- the loop ------------------------------------------------------------

    def serve(self, *, idle_timeout: float | None = None,
              poll_interval: float = 0.001,
              max_blocks: int | None = None) -> None:
        """Run until a SHUTDOWN frame arrives and every live request has
        reported (or `idle_timeout` seconds pass with no traffic — a test
        harness convenience). `max_blocks` returns after ingesting that
        many KV blocks WITHOUT draining — a canary/chaos control (the
        failover tests use it to die with requests in flight). Transport
        errors propagate: a dead frontend ends the worker, and a worker
        killed by fault injection simply stops reporting — the router's
        failover owns what happens next."""
        draining = False
        idle_since = time.monotonic()
        while True:
            self._poll_chaos()
            progressed, shutdown = self._ingest()
            draining = draining or shutdown
            if max_blocks is not None and self.stats["blocks"] >= max_blocks:
                return
            finished_by_ver = []
            for ver, srv in list(self._servers.items()):
                if srv._live or srv._pending:
                    finished_by_ver.append((ver, srv.step()))
                    progressed = True
            if finished_by_ver or self._first_pending:
                self._report(finished_by_ver)
            progressed |= self._pump_swap()
            self._retire_drained()
            telemetry.serve_queue_depth(
                "decode", sum(len(s._live) + len(s._pending)
                              for s in self._servers.values()))
            if draining and not any(s._live or s._pending
                                    for s in self._servers.values()):
                return
            if progressed:
                idle_since = time.monotonic()
            else:
                if (idle_timeout is not None
                        and time.monotonic() - idle_since > idle_timeout):
                    return
                time.sleep(poll_interval)

    def close(self) -> None:
        """Tear down the link (and the engine, when this worker owns one —
        the connect() path): comms closed, stream threads joined."""
        if self._receiver is not None:
            self._receiver.abort()
            self._receiver = None
        self.link.close()
        if self._net is not None:
            self._net.close()
            self._net = None


def connect(addr, model, params, *, slots: int, max_len: int,
            kv_codec: str | None = None, timeout: float = 60.0,
            net: transport.Net | None = None, weight_version: int = 0,
            **server_kwargs) -> DecodeWorker:
    """Wire this process to a frontend at `addr` ("host:port" or tuple) as
    a decode rank and return the ready DecodeWorker. `kv_codec` None
    defers to TPUNET_KV_WIRE_DTYPE (default int8). `weight_version` rides
    the HELLO signature — a stale value (readmission after dying mid-swap)
    is NOT a mismatch; the publisher catches the rank up."""
    from tpunet.config import Config

    if kv_codec is None:
        kv_codec = Config.from_env().kv_wire_dtype
    owns_net = net is None
    # Latency-class link: FIRST/RESULT frames are the router's TTFT signal
    # (see Router.__init__ on why the tier rides the latency lane).
    net = net or transport.Net(traffic_class="latency")
    hello = proto.Hello(proto.ROLE_DECODE, kv_codec, slots, max_len,
                        model.vocab, kv_mod.model_signature(model),
                        weight_version=weight_version)
    link = proto.wire_decode(addr, net, hello, timeout=timeout)
    worker = DecodeWorker(model, params, link, slots=slots, max_len=max_len,
                          kv_codec=kv_codec, weight_version=weight_version,
                          **server_kwargs)
    if owns_net:
        worker._net = net  # close() tears the engine down with the link
    return worker
