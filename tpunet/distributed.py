"""Process-group lifecycle for tpunet (the `jax.distributed`-style entry).

One global ring communicator per process, created from env or explicit
arguments. The JAX integration (tpunet.interop) routes cross-host DCN
collectives through it; in-pod (ICI) collectives stay with XLA
(`jax.lax.psum` over the device mesh) — matching the reference's division
of labor, where NCCL handled in-node NVLink and the plugin handled the
cross-host TCP path (SURVEY §5 "Distributed communication backend").
"""

from __future__ import annotations

import atexit
import sys
import threading

from tpunet.collectives import Communicator

_lock = threading.Lock()
_comm: Communicator | None = None
_comm_args: tuple | None = None


def initialize(
    coordinator: str | None = None,
    rank: int | None = None,
    world_size: int | None = None,
    wire_dtype: str | None = None,
    algo: str | None = None,
    traffic_class: str | None = None,
) -> Communicator:
    """Create (or return) the process-global communicator.

    Collective across processes: every process of the job must call it.
    Defaults from env: TPUNET_COORDINATOR, TPUNET_RANK/RANK,
    TPUNET_WORLD_SIZE/WORLD_SIZE. ``wire_dtype`` selects the collective
    wire compression codec ("f32"/"bf16"/"int8"; None defers to
    TPUNET_WIRE_DTYPE) — because the FFI custom-call collectives route
    through this communicator, it is also the codec every jitted dcn_*
    collective rides. ``algo`` pins the collective schedule
    ("auto"/"ring"/"rhd"/"tree"; None defers to TPUNET_ALGO, default auto
    — per-(collective, size, world) selection, docs/DESIGN.md §2c).
    ``traffic_class`` pins the QoS lane ("latency"/"bulk"/"control"; None
    defers to TPUNET_TRAFFIC_CLASS, default bulk — gradient comms keep the
    bulk class unchanged; the serving tier wires latency-class links).
    """
    global _comm, _comm_args
    args = (coordinator, rank, world_size, wire_dtype, algo, traffic_class)
    with _lock:
        if _comm is None:
            _comm = Communicator(coordinator, rank, world_size, wire_dtype,
                                 algo, traffic_class)
            _comm.set_as_default()  # FFI collectives resolve it at call time
            _comm_args = args
        elif args != _comm_args and any(a is not None for a in args):
            raise RuntimeError(
                f"tpunet.distributed already initialized with {_comm_args}; "
                f"got conflicting {args} — call finalize() first to "
                f"re-initialize"
            )
        return _comm


def is_initialized() -> bool:
    return _comm is not None


def global_communicator() -> Communicator:
    if _comm is None:
        raise RuntimeError(
            "tpunet.distributed.initialize() has not been called in this process"
        )
    return _comm


def finalize() -> None:
    global _comm, _comm_args
    with _lock:
        if _comm is not None:
            # Drop any pending async tickets registered for this comm (only
            # if interop was ever imported — keeps transport-only users free
            # of the jax import interop pulls in).
            interop = sys.modules.get("tpunet.interop")
            if interop is not None:
                interop._drop_pending_for(_comm)
            _comm.close()
            _comm = None
            _comm_args = None


def rank() -> int:
    return global_communicator().rank


def world_size() -> int:
    return global_communicator().world_size


@atexit.register
def _cleanup() -> None:  # pragma: no cover - interpreter teardown
    try:
        finalize()
    except Exception:
        pass
