"""Continuous batching — a slot server over the per-row decode cache.

`generate()` advances one batch in lockstep: every sequence prefills
together and the call returns when the LAST one finishes, so short
requests wait on long ones and finished rows burn MXU cycles. The
`BatchServer` removes both: the model runs with `per_row_cache=True`
(each batch row carries its own `cache_index`), so rows are independent
sequences — a finished row's slot is re-prefilled for the next queued
request while the other rows keep decoding, and nothing ever waits.

TPU-first shape discipline: the decode step is ONE jitted program of
static shape (slots, 1) regardless of which slots are live — occupancy
changes never recompile. Slot refill is a second jitted program per
distinct prompt length (row slice → reset index → kernel-routed prefill
→ row write-back); bucket or pad prompts to a few lengths to bound
retraces, exactly like any static-shape serving stack. Idle rows decode
garbage tokens into their own dead cache rows — per-row masking keeps
them from touching live rows, a refill resets the row's index to 0, and
the stale K/V above the new sequence's frontier is masked until
overwritten (`key_pos <= q_pos`, the same argument that makes
speculative rollback sound).

Speculative mode (`draft_model=`): each decode window becomes
`steps_per_call` SPECULATIVE ROUNDS — draft gamma tokens per slot, verify
in one target forward, commit each row's own accepted prefix plus the
fix/bonus token (same exactness machinery as `speculative_generate`:
shared filtered distribution, residual sampling, ring stash/restore).
A dispatch then commits up to gamma+1 tokens per row instead of one;
greedy outputs are bitwise `generate()`'s. The draft cache rides the same
slot lifecycle (row surgery prefills both).

Disaggregated mode (`submit_kv()`): a request whose prompt K/V was
computed on a PREFILL RANK and shipped over the transport (tpunet.serve)
refills its slot through a jitted adopt program — shipped prefix written
into the row, index set, first token sampled from the shipped logits —
instead of re-running prefill. On an exact (f32) KV wire the adopted state
is bitwise what local prefill would have produced, so greedy outputs
cannot be told apart from single-host serving (docs/DESIGN.md §10).

The reference repo has no inference path at all (it is a transport;
SURVEY §2.3); this is framework capability above it.
"""

from __future__ import annotations

from collections import deque
from functools import partial
from itertools import count

import jax
import jax.numpy as jnp
import numpy as np

from tpunet.models.generate import (_get_cache_index, _kv_leaves,
                                    _make_spec_round_core, _map_cache_index,
                                    _prefill, _set_cache_index, _spec_ring_ok,
                                    _validate_sampling, filtered_logits,
                                    init_cache, make_sampler)


def _clamp_cache_index(cache, cap):
    """Clamp every cache_index leaf to cap. Idle (freed, not-yet-refilled)
    slots keep decoding garbage every window and their per-row index would
    otherwise grow without bound — int32-wrapping after ~2^31 idle steps
    and leaning on scatter out-of-bounds drop semantics for an unbounded
    range of positions. Clamped, an idle row's index parks at cap: its
    (single, constant) write position cap is one-past-end (dropped), the
    overflow NaN-poison still marks the row's output as garbage, and a
    refill resets the index anyway. Live rows are unaffected — submit()
    bounds prompt + max_new <= max_len, so a live row's index never
    exceeds cap."""
    return _map_cache_index(cache, lambda leaf: jnp.minimum(leaf, cap))


class BatchServer:
    """Continuous-batching decode server.

    submit() enqueues a request; slots are assigned at the next
    step()/run() boundary, so a burst of submissions prefills as one
    batched dispatch. step() advances every live slot one token (or one
    speculative ROUND of up to gamma+1 tokens when a draft_model is
    given) and returns the requests that finished. Greedy by default;
    temperature/top-k/top-p sample per-row from the device-carried key
    chain.
    """

    def __init__(self, model, params, *, slots: int, max_len: int,
                 temperature: float = 0.0, top_k: int | None = None,
                 top_p: float | None = None, eos_id: int | None = None,
                 rng=None, prefill_chunk: int | None = None,
                 steps_per_call: int = 1, refill_coalesce: int = 1,
                 draft_model=None, draft_params=None, gamma: int = 4,
                 on_first_token=None):
        _validate_sampling(temperature, top_k, top_p)
        if (draft_model is None) != (draft_params is None):
            raise ValueError("draft_model and draft_params come together")
        if draft_model is not None and gamma < 1:
            raise ValueError(f"gamma must be >= 1, got {gamma}")
        if draft_model is not None and getattr(draft_model, "n_experts", 0):
            raise ValueError("draft_model must be dense (same MoE "
                             "batch-coupling argument as the target)")
        if (draft_model is not None
                and draft_model.vocab != model.vocab):
            raise ValueError("draft vocab must match the target")
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if steps_per_call < 1:
            raise ValueError(
                f"steps_per_call must be >= 1, got {steps_per_call}")
        if refill_coalesce < 1:
            raise ValueError(
                f"refill_coalesce must be >= 1, got {refill_coalesce}")
        if getattr(model, "n_experts", 0):
            # MoE capacity is computed batch-wide (t = b*s slots claimed by
            # a cross-row cumsum), so other rows' tokens - including idle
            # garbage - change which of a live row's tokens get dropped:
            # the per-slot parity contract cannot hold. Reject loudly.
            raise ValueError(
                "BatchServer requires a dense model: MoE capacity couples "
                "rows (batch-wide expert slots), breaking per-slot "
                "independence")
        self.model = model
        self.params = params
        self.slots, self.max_len = slots, max_len
        # Refill batching: a freed slot is NOT refilled until at least
        # this many slots are free (or nothing is decoding, or the queue
        # would drain anyway). Singleton (1, p) prefills waste matmul
        # width (measured at d256: 12 singles ~100 ms vs 4 batched (4, p)
        # ~53 ms), BUT holding a slot costs idle decode windows until a
        # partner frees, and when retirements are spread in time that
        # idleness exceeds the batching gain (measured: coalesce=2 LOST
        # 3-6% end-to-end on both toy and d256 configs). Default 1 =
        # refill immediately; raise it only when retirements cluster
        # (uniform max_new, bursty arrivals).
        self.refill_coalesce = min(refill_coalesce, slots)
        self.eos_id = eos_id
        self._sampling = (temperature, top_k, top_p)
        self._prefill_chunk = prefill_chunk
        self._dm = model.clone(
            decode=True, per_row_cache=True,
            decode_ring_cache=(_spec_ring_ok(model, gamma)
                               if draft_model is not None
                               else getattr(model, "decode_ring_cache",
                                            True)))
        # Speculative rounds overshoot the committed frontier by up to
        # gamma: the verify block must never cross the cache capacity for
        # a LIVE row, so spec mode adds gamma + 1 slack rows of K/V (the
        # submit() contract stays p + max_new <= max_len).
        cache_cap = max_len + (gamma + 1 if draft_model is not None else 0)
        self._cache = init_cache(self._dm, slots, cache_cap)
        self._draft = draft_model
        if draft_model is not None:
            self._dm_draft = draft_model.clone(
                decode=True, per_row_cache=True,
                decode_ring_cache=_spec_ring_ok(draft_model, gamma))
            self._dcache = init_cache(self._dm_draft, slots, cache_cap)
        self._free = list(range(slots))
        self._live: dict[int, dict] = {}       # slot -> request record
        self._pending: list[dict] = []
        self._ids = count()
        # Device-resident loop state: the per-slot last tokens and the rng
        # key live ON DEVICE and are donated through every jitted call —
        # the host never re-uploads them and never dispatches a bare
        # jax.random.split between steps. The only host<->device traffic
        # on the decode path is the one necessary window readback.
        self._toks = jnp.zeros(slots, jnp.int32)
        self._key = rng if rng is not None else jax.random.PRNGKey(0)
        self._done_buffer: list[dict] = []  # finished before step() drained
        self.stats = {"decode_windows": 0, "prefills": 0, "kv_adopts": 0}
        # Serving-tier hook: called with a request's id the moment its FIRST
        # token is committed (TTFT instrumentation for the disaggregated
        # decode worker). Host-side, after the window readback — never
        # inside a jitted program.
        self._on_first_token = on_first_token

        sample = make_sampler(temperature, top_k, top_p)

        # The cache is the dominant inference resident (slots x max_len x
        # layers); donating it keeps ONE buffer alive across the per-token
        # step instead of copy-in/copy-out each call (generate() gets this
        # for free by scanning inside one jit; the server's step is the
        # jit boundary). Donation is a no-op on CPU.
        #
        # steps_per_call > 1 scans that many micro-steps INSIDE the jit
        # (one dispatch + one host sync per window instead of per token) —
        # the lever that amortizes host-loop overhead at small step costs.
        # The scheduling granularity coarsens with it: retirements and
        # refills land at window boundaries, and a row that finishes
        # mid-window decodes garbage for the remainder (discarded; its
        # refill resets the row).
        max_len_cap = max_len

        # Both jits CLOSE OVER params: the server's weights are fixed at
        # construction, and passing the 10s-of-leaves param tree through
        # every call costs a flatten + cache lookup per dispatch — real
        # money when the step itself is ~1 ms.
        params_c = params

        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def decode_step(cache, toks, key):
            key, sub = jax.random.split(key)

            def body(carry, k):
                cache, tok = carry
                logits, mut = self._dm.apply(
                    {"params": params_c, "cache": cache}, tok[:, None],
                    mutable=["cache"])
                nxt = sample(logits[:, -1, :], k)
                return (mut["cache"], nxt), nxt

            (cache, toks), toks_out = jax.lax.scan(
                body, (cache, toks), jax.random.split(sub, steps_per_call))
            cache = _clamp_cache_index(cache, max_len_cap)
            # (slots, window) readback + the carried device state.
            return cache, toks, toks_out.swapaxes(0, 1), key

        @partial(jax.jit, donate_argnums=(0, 1), static_argnames=("chunk",))
        def prefill_slots(cache, toks, prompts, rows, key, chunk):
            # Row surgery, n rows at once: gather the claimed slots out of
            # every cache leaf, reset their indexes (the rows may hold
            # dead sequences' frontiers), prefill the (n, p) prompts
            # through the shared kernel-routed path, scatter the rows
            # back. One dispatch per same-length refill group.
            key, sub = jax.random.split(key)
            row = jax.tree.map(lambda a: a[rows], cache)
            row = _set_cache_index(row, 0)
            row, last = _prefill(self._dm, params_c, row, prompts, chunk)
            cache = jax.tree.map(
                lambda a, rw: a.at[rows].set(rw), cache, row)
            tok = sample(last, sub)  # (n,)
            toks = toks.at[rows].set(tok)
            return cache, toks, tok, key

        @partial(jax.jit, donate_argnums=(0, 1))
        def adopt_slots(cache, toks, kv, last, rows, key):
            # Disaggregated-serving refill: install SHIPPED prompt K/V into
            # the claimed slots instead of re-running prefill. `kv` is a
            # tuple of (n, p, kv_heads, head_dim) blocks in _kv_leaves
            # order (the prefill rank extracted them in the same order);
            # `last` is the prefill's final-position logits (n, vocab), so
            # the first token is sampled EXACTLY like the local-prefill
            # path (greedy outputs bitwise-equal to single-host serving on
            # an exact KV wire). Stale K/V above position p in the adopted
            # rows is masked by the decode step until overwritten — the
            # same argument that makes ordinary slot refill sound.
            key, sub = jax.random.split(key)
            plen = kv[0].shape[1]
            span = jnp.arange(plen)
            blocks = iter(kv)

            def fix(path, leaf):
                name = (path[-1].key if hasattr(path[-1], "key")
                        else str(path[-1]))
                if name in ("cached_key", "cached_value"):
                    blk = next(blocks).astype(leaf.dtype)
                    return leaf.at[rows[:, None], span[None, :]].set(blk)
                if name == "cache_index":
                    return leaf.at[rows].set(
                        jnp.asarray(plen, leaf.dtype))
                return leaf
            cache = jax.tree_util.tree_map_with_path(fix, cache)
            tok = sample(last, sub)  # (n,)
            toks = toks.at[rows].set(tok)
            return cache, toks, tok, key

        self._adopt_slots = adopt_slots

        if draft_model is not None:
            greedy = temperature == 0.0
            t_ring = _spec_ring_ok(model, gamma)
            d_ring = _spec_ring_ok(draft_model, gamma)
            draft_params_c = draft_params
            rows_i = jnp.arange(slots)
            spec_cap = max_len_cap + gamma + 1

            def probs_of(logits):
                return jax.nn.softmax(
                    filtered_logits(logits, temperature, top_k, top_p),
                    axis=-1)

            round_core = _make_spec_round_core(
                self._dm, self._dm_draft, params_c, draft_params_c, gamma,
                greedy, probs_of, t_ring, d_ring)

            def spec_round(carry, key):
                # One speculative round over every slot (live or garbage):
                # draft gamma, verify in ONE target forward, commit each
                # row's own accepted prefix + fix/bonus token. The
                # exactness machinery is THE SHARED CORE
                # (_make_spec_round_core) speculative_generate uses — the
                # server only owns the schedule: per-row commits
                # (adjust_n identity), capacity parking, and the
                # host-side eos/max_new cutting in _append_tokens
                # (garbage rows are discarded by the occupancy snapshot).
                t_cache, d_cache, tok = carry
                k_draft, k_accept, k_fix = jax.random.split(key, 3)
                idx0 = _get_cache_index(t_cache)  # (slots,) round frontier

                t_cache, d_cache, w, _, n_eff = round_core(
                    t_cache, d_cache, tok, idx0, k_draft, k_accept, k_fix,
                    lambda n_raw: n_raw,          # pure per-row commits
                    lambda n_eff: idx0 + n_eff + 1)
                counts = n_eff + 1
                # Idle rows' frontiers park at the cap (same clamp
                # rationale as the plain path; spec_cap includes the
                # overshoot slack so live rows never clamp).
                new_idx = jnp.minimum(idx0 + counts, spec_cap)
                t_cache = _set_cache_index(t_cache, new_idx)
                d_cache = _set_cache_index(d_cache, new_idx)
                tok_next = w[rows_i, n_eff]
                return (t_cache, d_cache, tok_next), (w, counts)

            @partial(jax.jit, donate_argnums=(0, 1, 2, 3))
            def spec_decode_step(t_cache, d_cache, toks, key):
                key, sub = jax.random.split(key)
                (t_cache, d_cache, toks), (w, counts) = jax.lax.scan(
                    spec_round, (t_cache, d_cache, toks),
                    jax.random.split(sub, steps_per_call))
                # (slots, rounds, gamma+1) committed blocks + per-round
                # per-row commit counts.
                return (t_cache, d_cache, toks, w.swapaxes(0, 1),
                        counts.swapaxes(0, 1), key)

            @partial(jax.jit, donate_argnums=(0, 1, 2),
                     static_argnames=("chunk",))
            def spec_prefill_slots(t_cache, d_cache, toks, prompts, rows,
                                   key, chunk):
                # Same row surgery as the plain path, on BOTH caches: the
                # draft must hold the prompt K/V before it can propose.
                key, sub = jax.random.split(key)
                row = jax.tree.map(lambda a: a[rows], t_cache)
                row = _set_cache_index(row, 0)
                row, last = _prefill(self._dm, params_c, row, prompts,
                                     chunk)
                t_cache = jax.tree.map(
                    lambda a, rw: a.at[rows].set(rw), t_cache, row)
                drow = jax.tree.map(lambda a: a[rows], d_cache)
                drow = _set_cache_index(drow, 0)
                drow, _ = _prefill(self._dm_draft, draft_params_c, drow,
                                   prompts, chunk)
                d_cache = jax.tree.map(
                    lambda a, rw: a.at[rows].set(rw), d_cache, drow)
                tok = sample(last, sub)  # (n,)
                toks = toks.at[rows].set(tok)
                return t_cache, d_cache, toks, tok, key

            self._spec_decode_step = spec_decode_step
            self._spec_prefill_slots = spec_prefill_slots
            self.stats["spec_rounds"] = 0
            self.stats["spec_committed"] = 0
        self._decode_step = decode_step
        self._prefill_slots = prefill_slots

    def submit(self, prompt, max_new_tokens: int) -> int:
        """Enqueue one request; returns its id. Slot assignment happens at
        the next step()/run() boundary — deferring it there lets a burst
        of submissions prefill as ONE batched (n, p) dispatch instead of n
        singletons (submit-time assignment made the documented startup
        batching unreachable)."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size < 1:
            raise ValueError(f"prompt must be 1-D non-empty, got "
                             f"shape {prompt.shape}")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new ({max_new_tokens}) "
                f"exceeds max_len {self.max_len}")
        # Upload at submit time (async): the refill dispatch later reads
        # a device array instead of paying a device_put on the refill
        # path — the host-side equivalent of pinning the request queue.
        req = {"id": next(self._ids), "prompt": prompt,
               "prompt_dev": jnp.asarray(prompt[None]),
               "max_new": max_new_tokens, "chunks": [], "n_out": 0}
        self._pending.append(req)
        return req["id"]

    def kv_leaf_shapes(self, plen: int) -> list[tuple]:
        """Expected shapes of the per-leaf KV blocks `submit_kv` installs
        for a prompt of length `plen`, in shipping order: one
        (plen, kv_heads, head_dim) entry per cached_key/cached_value leaf
        (tree-flatten order — the prefill tier extracts in the same
        order)."""
        return [(plen,) + tuple(leaf.shape[2:])
                for leaf in _kv_leaves(self._cache)]

    def submit_kv(self, prompt, max_new_tokens: int, kv_rows, last_logits) -> int:
        """Enqueue one request whose prompt K/V was computed ELSEWHERE (a
        prefill rank) and shipped here: the refill installs `kv_rows` into
        the claimed slot instead of re-running prefill — the decode half
        of the disaggregated serving tier (tpunet.serve). `kv_rows` is a
        list of numpy arrays matching kv_leaf_shapes(len(prompt));
        `last_logits` is the prefill's final-position logit row (vocab,),
        from which the first token is sampled exactly like the
        local-prefill path (greedy outputs are bitwise-equal to
        single-host serving when the KV wire is exact)."""
        if self._draft is not None:
            raise ValueError(
                "submit_kv requires a non-speculative server: the draft "
                "cache has no shipped prompt K/V to propose from")
        if getattr(self.model, "attn_window", None) is not None:
            raise ValueError(
                "submit_kv requires a full-capacity cache (attn_window "
                "models keep a rolling ring whose slot->position mapping "
                "is not the shipped prefix layout)")
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size < 1:
            raise ValueError(f"prompt must be 1-D non-empty, got "
                             f"shape {prompt.shape}")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new ({max_new_tokens}) "
                f"exceeds max_len {self.max_len}")
        shapes = self.kv_leaf_shapes(prompt.size)
        if len(kv_rows) != len(shapes):
            raise ValueError(f"expected {len(shapes)} KV blocks, "
                             f"got {len(kv_rows)}")
        kv_rows = [np.asarray(b, np.float32) for b in kv_rows]
        for i, (blk, want) in enumerate(zip(kv_rows, shapes)):
            if tuple(blk.shape) != want:
                raise ValueError(
                    f"KV block {i} has shape {tuple(blk.shape)}, "
                    f"expected {want}")
        last_logits = np.asarray(last_logits, np.float32)
        if last_logits.shape != (self.model.vocab,):
            raise ValueError(
                f"last_logits must be ({self.model.vocab},), got "
                f"{last_logits.shape}")
        req = {"id": next(self._ids), "prompt": prompt,
               "max_new": max_new_tokens, "chunks": [], "n_out": 0,
               "kv_rows": kv_rows, "kv_logits": last_logits}
        self._pending.append(req)
        return req["id"]

    def _fill_slots(self, defer: bool = False) -> None:
        if not (self._free and self._pending):
            return
        if (len(self._free) < self.refill_coalesce and self._live
                and len(self._pending) > len(self._free)):
            return  # hold out for a batched refill (see refill_coalesce)
        # Claim every (request, slot) pair now, then prefill all claims of
        # the SAME prompt length in ONE batched dispatch (n-row gather ->
        # reset -> (n, p) prefill -> n-row scatter). Startup fills all
        # slots in one call instead of `slots`; steady-state refills are
        # usually singletons. Retraces are bounded by distinct (n, p)
        # pairs — bucket prompt lengths as with any static-shape stack.
        claims = []
        while self._free and self._pending:
            claims.append((self._pending.pop(0), self._free.pop()))
        by_len: dict[int, list] = {}
        by_len_kv: dict[int, list] = {}
        for req, r in claims:
            target = by_len_kv if "kv_rows" in req else by_len
            target.setdefault(req["prompt"].size, []).append((req, r))

        def commit(group, tok):
            if defer:
                # Pipelined mode: don't sync on the refill's sampled
                # tokens (that would drain every in-flight window behind
                # them). Hold the device vector; the next absorb resolves
                # it BEFORE appending that window's tokens, so outputs and
                # retirement decisions are unchanged — only their
                # host-side timing shifts to the next window boundary.
                holder = {"dev": tok, "np": None}  # one readback, shared
                for i, (req, r) in enumerate(group):
                    self._live[r] = req
                    req["_pending"] = (holder, i)
            else:
                arr = np.asarray(tok)
                for i, (req, r) in enumerate(group):
                    self._live[r] = req
                    self._append_tokens(r, req, arr[i: i + 1])

        for group in by_len.values():
            reqs = [q for q, _ in group]
            rows = jnp.asarray(np.array([r for _, r in group], np.int32))
            prompts = (reqs[0]["prompt_dev"] if len(reqs) == 1
                       else jnp.concatenate(
                           [q["prompt_dev"] for q in reqs], axis=0))
            if self._draft is not None:
                (self._cache, self._dcache, self._toks, tok,
                 self._key) = self._spec_prefill_slots(
                    self._cache, self._dcache, self._toks, prompts, rows,
                    self._key, self._prefill_chunk)
            else:
                (self._cache, self._toks, tok,
                 self._key) = self._prefill_slots(
                    self._cache, self._toks, prompts, rows,
                    self._key, self._prefill_chunk)
            self.stats["prefills"] += len(group)
            commit(group, tok)
        for group in by_len_kv.values():
            # Shipped-KV refill (disaggregated serving): one batched adopt
            # dispatch per same-length group — the row surgery writes the
            # shipped prefix instead of recomputing it.
            reqs = [q for q, _ in group]
            rows = jnp.asarray(np.array([r for _, r in group], np.int32))
            kv = tuple(
                jnp.asarray(np.stack([q["kv_rows"][i] for q in reqs]))
                for i in range(len(reqs[0]["kv_rows"])))
            last = jnp.asarray(np.stack([q["kv_logits"] for q in reqs]))
            for q in reqs:  # the device copies above own the data now
                q.pop("kv_rows")
                q.pop("kv_logits")
            (self._cache, self._toks, tok,
             self._key) = self._adopt_slots(
                self._cache, self._toks, kv, last, rows, self._key)
            self.stats["kv_adopts"] += len(group)
            commit(group, tok)

    def _append_tokens(self, r: int, req: dict, toks_np) -> None:
        """Commit a window's tokens to a request — vectorized: cut at
        max_new, then at the first eos, in one numpy pass instead of a
        Python loop per token. Retires the request (freeing its slot into
        the done buffer) when either bound is hit; a request can finish at
        ANY commit point, including its first prefill-sampled token."""
        take = min(req["max_new"] - req["n_out"], len(toks_np))
        first = req["n_out"] == 0
        chunk = toks_np[:take]
        if self.eos_id is not None:
            hits = np.nonzero(chunk == self.eos_id)[0]
            if hits.size:
                chunk = chunk[: hits[0] + 1]  # keep the eos itself
        req["chunks"].append(chunk)
        req["n_out"] += len(chunk)
        if first and len(chunk) and self._on_first_token is not None:
            self._on_first_token(req["id"])  # TTFT hook (serving tier)
        if (req["n_out"] >= req["max_new"]
                or (self.eos_id is not None and chunk.size
                    and chunk[-1] == self.eos_id)):
            del self._live[r]
            self._free.append(r)
            self._done_buffer.append(
                {"id": req["id"], "prompt": req["prompt"],
                 "tokens": np.concatenate(req["chunks"]).astype(np.int32)})

    def _dispatch_window(self):
        """Issue one decode window WITHOUT reading it back; returns the
        device payload plus a {slot: request_id} snapshot of occupancy at
        dispatch time (a later refill recycles the slot for a different
        request — that window's tokens for the slot are garbage). Payload:
        plain mode (window, None); speculative mode (w, counts) with w
        (slots, rounds, gamma+1) and per-round per-row commit counts."""
        if self._draft is not None:
            (self._cache, self._dcache, self._toks, w, counts,
             self._key) = self._spec_decode_step(
                self._cache, self._dcache, self._toks, self._key)
            self.stats["decode_windows"] += 1
            return (w, counts), {r: req["id"]
                                 for r, req in self._live.items()}
        self._cache, self._toks, window, self._key = self._decode_step(
            self._cache, self._toks, self._key)
        self.stats["decode_windows"] += 1
        return (window, None), {r: req["id"]
                                for r, req in self._live.items()}

    def _absorb_window(self, payload, ids_at_dispatch) -> None:
        window, counts = payload
        window = np.asarray(window)  # readback
        counts = None if counts is None else np.asarray(counts)
        for r, rid in ids_at_dispatch.items():
            req = self._live.get(r)
            if req is None or req["id"] != rid:
                continue  # retired or recycled since this window launched
            if "_pending" in req:
                # Deferred prefill token: by now its compute long finished
                # (it was dispatched before this window). The group's
                # token vector is read back once and shared.
                holder, i = req.pop("_pending")
                if holder["np"] is None:
                    holder["np"] = np.asarray(holder["dev"])
                self._append_tokens(r, req, holder["np"][i: i + 1])
                if r not in self._live:
                    continue
            if counts is None:
                self._append_tokens(r, req, window[r])
                continue
            for j in range(window.shape[1]):  # speculative rounds
                c = int(counts[r, j])
                self.stats["spec_rounds"] += 1
                self.stats["spec_committed"] += c
                self._append_tokens(r, req, window[r, j, :c])
                if r not in self._live:
                    break  # rest of this row's rounds are garbage

    def step(self) -> list[dict]:
        """Advance every live slot one token; returns the requests that
        finished this step as {"id", "prompt", "tokens"} dicts (freed
        slots are immediately refilled from the queue)."""
        self._fill_slots()
        if self._live:
            window, ids = self._dispatch_window()
            self._absorb_window(window, ids)
            self._fill_slots()
        finished, self._done_buffer = self._done_buffer, []
        return finished

    def run(self, *, pipeline: int = 1) -> dict[int, np.ndarray]:
        """Drive the server until every submitted request finishes;
        returns {request_id: generated tokens}.

        `pipeline` keeps that many decode windows in flight: window k+1 is
        dispatched BEFORE window k's readback, so host bookkeeping (token
        appends, retirement, refill decisions) overlaps device compute
        instead of serializing with it. A window launched before a refill
        simply decodes garbage in the recycled slot (discarded via the
        dispatch-time occupancy snapshot) and the refilled request joins
        one window later — greedy outputs are unchanged (each request's
        tokens depend only on its own prefix); with temperature > 0 the
        carried key chain advances differently across pipeline settings,
        so sampled outputs are schedule-dependent (still exactly
        distributed). pipeline=1 (the default) is the strict
        alternate-dispatch-absorb loop — right for single-core hosts and
        CPU testing, where host and compute serialize anyway and extra
        in-flight windows just waste micro-steps. pipeline=2 is the TPU
        serving setting: compute runs on the chip, so the host's
        absorb/refill work for window k hides entirely under window k+1's
        device time."""
        if pipeline < 1:
            raise ValueError(f"pipeline must be >= 1, got {pipeline}")
        results = {}
        inflight = deque()
        # defer only when windows are actually kept in flight: at
        # pipeline=1 nothing is behind the prefill to stall, and the
        # immediate readback lets a request that finishes on its
        # prefill-sampled token (max_new=1, eos first) retire with ZERO
        # decode windows; deferred it would cost a whole discarded window.
        defer = pipeline >= 2
        while (self._live or self._pending or self._done_buffer
               or inflight):
            finished, self._done_buffer = self._done_buffer, []
            for rec in finished:
                results[rec["id"]] = rec["tokens"]
            self._fill_slots(defer=defer)  # no-op without free+pending
            while self._live and len(inflight) < pipeline:
                inflight.append(self._dispatch_window())
            if inflight:
                window, ids = inflight.popleft()
                self._absorb_window(window, ids)
                self._fill_slots(defer=defer)
        return results
