"""Continuous batching — a slot server over the per-row decode cache.

`generate()` advances one batch in lockstep: every sequence prefills
together and the call returns when the LAST one finishes, so short
requests wait on long ones and finished rows burn MXU cycles. The
`BatchServer` removes both: the model runs with `per_row_cache=True`
(each batch row carries its own `cache_index`), so rows are independent
sequences — a finished row's slot is re-prefilled for the next queued
request while the other rows keep decoding, and nothing ever waits.

TPU-first shape discipline: the decode step is ONE jitted program of
static shape (slots, 1) regardless of which slots are live — occupancy
changes never recompile. Slot refill is a second jitted program per
distinct prompt length (row slice → reset index → kernel-routed prefill
→ row write-back); bucket or pad prompts to a few lengths to bound
retraces, exactly like any static-shape serving stack. Idle rows decode
garbage tokens into their own dead cache rows — per-row masking keeps
them from touching live rows, a refill resets the row's index to 0, and
the stale K/V above the new sequence's frontier is masked until
overwritten (`key_pos <= q_pos`, the same argument that makes
speculative rollback sound).

The reference repo has no inference path at all (it is a transport;
SURVEY §2.3); this is framework capability above it.
"""

from __future__ import annotations

from functools import partial
from itertools import count

import jax
import jax.numpy as jnp
import numpy as np

from tpunet.models.generate import (_prefill, _set_cache_index,
                                    _validate_sampling, init_cache,
                                    make_sampler)


class BatchServer:
    """Continuous-batching decode server.

    submit() enqueues a request (assigned to a slot immediately when one
    is free); step() advances every live slot one token and returns the
    requests that finished. Greedy by default; temperature/top-k/top-p
    sample per-row with a fresh fold of `rng` each step.
    """

    def __init__(self, model, params, *, slots: int, max_len: int,
                 temperature: float = 0.0, top_k: int | None = None,
                 top_p: float | None = None, eos_id: int | None = None,
                 rng=None, prefill_chunk: int | None = None,
                 steps_per_call: int = 1):
        _validate_sampling(temperature, top_k, top_p)
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        if steps_per_call < 1:
            raise ValueError(
                f"steps_per_call must be >= 1, got {steps_per_call}")
        if getattr(model, "n_experts", 0):
            # MoE capacity is computed batch-wide (t = b*s slots claimed by
            # a cross-row cumsum), so other rows' tokens - including idle
            # garbage - change which of a live row's tokens get dropped:
            # the per-slot parity contract cannot hold. Reject loudly.
            raise ValueError(
                "BatchServer requires a dense model: MoE capacity couples "
                "rows (batch-wide expert slots), breaking per-slot "
                "independence")
        self.model = model
        self.params = params
        self.slots, self.max_len = slots, max_len
        self.eos_id = eos_id
        self._sampling = (temperature, top_k, top_p)
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)
        self._prefill_chunk = prefill_chunk
        self._dm = model.clone(decode=True, per_row_cache=True)
        self._cache = init_cache(self._dm, slots, max_len)
        self._free = list(range(slots))
        self._live: dict[int, dict] = {}       # slot -> request record
        self._pending: list[dict] = []
        self._ids = count()
        self._last_tok = np.zeros(slots, np.int32)
        self._done_buffer: list[dict] = []  # finished before step() drained
        self.stats = {"decode_windows": 0, "prefills": 0}

        sample = make_sampler(temperature, top_k, top_p)

        # The cache is the dominant inference resident (slots x max_len x
        # layers); donating it keeps ONE buffer alive across the per-token
        # step instead of copy-in/copy-out each call (generate() gets this
        # for free by scanning inside one jit; the server's step is the
        # jit boundary). Donation is a no-op on CPU.
        #
        # steps_per_call > 1 scans that many micro-steps INSIDE the jit
        # (one dispatch + one host sync per window instead of per token) —
        # the lever that amortizes host-loop overhead at small step costs.
        # The scheduling granularity coarsens with it: retirements and
        # refills land at window boundaries, and a row that finishes
        # mid-window decodes garbage for the remainder (discarded; its
        # refill resets the row).
        @partial(jax.jit, donate_argnums=(1,))
        def decode_step(params, cache, toks, key):
            def body(carry, key):
                cache, tok = carry
                logits, mut = self._dm.apply(
                    {"params": params, "cache": cache}, tok[:, None],
                    mutable=["cache"])
                nxt = sample(logits[:, -1, :], key)
                return (mut["cache"], nxt), nxt

            (cache, _), toks_out = jax.lax.scan(
                body, (cache, toks), jax.random.split(key, steps_per_call))
            return cache, toks_out.swapaxes(0, 1)  # (slots, window)

        @partial(jax.jit, donate_argnums=(1,), static_argnames=("chunk",))
        def prefill_slot(params, cache, prompt, r, key, chunk):
            # Row surgery: slice slot r out of every cache leaf, reset its
            # index (the row may hold a dead sequence's frontier), prefill
            # through the shared kernel-routed path, write the row back.
            row = jax.tree.map(
                lambda a: jax.lax.dynamic_slice_in_dim(a, r, 1, 0),
                cache)
            row = _set_cache_index(row, 0)
            row, last = _prefill(self._dm, params, row, prompt, chunk)
            cache = jax.tree.map(
                lambda a, rw: jax.lax.dynamic_update_slice_in_dim(
                    a, rw, r, 0),
                cache, row)
            return cache, sample(last, key)

        self._decode_step = decode_step
        self._prefill_slot = prefill_slot

    def _next_key(self):
        self._rng, key = jax.random.split(self._rng)
        return key

    def submit(self, prompt, max_new_tokens: int) -> int:
        """Enqueue one request; returns its id. Assigned to a slot now if
        one is free, otherwise when step() frees one."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size < 1:
            raise ValueError(f"prompt must be 1-D non-empty, got "
                             f"shape {prompt.shape}")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if prompt.size + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new ({max_new_tokens}) "
                f"exceeds max_len {self.max_len}")
        req = {"id": next(self._ids), "prompt": prompt,
               "max_new": max_new_tokens, "out": []}
        self._pending.append(req)
        self._fill_slots()
        return req["id"]

    def _fill_slots(self) -> None:
        while self._free and self._pending:
            req = self._pending.pop(0)
            r = self._free.pop()
            self._cache, tok = self._prefill_slot(
                self.params, self._cache, jnp.asarray(req["prompt"][None]),
                jnp.int32(r), self._next_key(), self._prefill_chunk)
            self.stats["prefills"] += 1
            first = int(tok[0])
            req["out"].append(first)
            self._last_tok[r] = first
            self._live[r] = req
            self._retire_if_done(r)

    def _retire_if_done(self, r: int) -> None:
        # A request can finish at ANY commit point — including its very
        # first token, sampled during prefill — so retirement lands in a
        # buffer that step() drains, not in step()'s local list.
        req = self._live[r]
        if (len(req["out"]) >= req["max_new"]
                or (self.eos_id is not None
                    and req["out"][-1] == self.eos_id)):
            del self._live[r]
            self._free.append(r)
            self._done_buffer.append(
                {"id": req["id"], "prompt": req["prompt"],
                 "tokens": np.asarray(req["out"], np.int32)})

    def step(self) -> list[dict]:
        """Advance every live slot one token; returns the requests that
        finished this step as {"id", "prompt", "tokens"} dicts (freed
        slots are immediately refilled from the queue)."""
        if not self._live and self._pending:
            self._fill_slots()
        if self._live:
            toks = jnp.asarray(self._last_tok)  # idle rows decode garbage
            self._cache, window = self._decode_step(
                self.params, self._cache, toks, self._next_key())
            self.stats["decode_windows"] += 1
            window = np.asarray(window)  # (slots, steps_per_call)
            for r in list(self._live):
                req = self._live[r]
                for tok in window[r]:
                    req["out"].append(int(tok))
                    self._last_tok[r] = int(tok)
                    self._retire_if_done(r)
                    if r not in self._live:
                        break  # rest of this row's window is garbage
            self._fill_slots()
        finished, self._done_buffer = self._done_buffer, []
        return finished

    def run(self) -> dict[int, np.ndarray]:
        """Drive step() until every submitted request finishes; returns
        {request_id: generated tokens}."""
        results = {}
        # _done_buffer may already hold requests that retired during
        # submit()'s prefill (max_new=1, or an eos first token) - step()
        # drains it even when nothing is live.
        while self._live or self._pending or self._done_buffer:
            for rec in self.step():
                results[rec["id"]] = rec["tokens"]
        return results
