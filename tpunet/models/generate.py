"""Autoregressive generation with a per-layer KV cache.

TPU-first inference loop for the Transformer family: one prefill call
scores the whole prompt (MXU-sized matmuls, causal), then a `lax.scan`
decodes token-by-token against the flax "cache" collection that
`SelfAttention(decode=True)` maintains (full-capacity buffers updated with
`dynamic_update_slice`; windowed models default to a TRUE rolling ring
buffer sized min(window, cap), written by modular scatter — either way
static shapes, so the whole loop jits and the per-step executable is
reused). GQA models cache only n_kv_heads, so the cache — the resident
that limits batch at inference — shrinks by n_heads/n_kv_heads.

The reference repo has no inference path at all (it is a transport;
SURVEY §2.3); this is framework capability above it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_cache(model, batch: int, max_len: int):
    """Allocate the decode cache for `batch` sequences of capacity
    `max_len` (prompt + generated). Shapes come from `eval_shape` — no
    second parameter set is materialized and no forward FLOPs run (a real
    init would execute a full (batch, max_len) causal forward, O(max_len²)
    attention memory, just to throw the result away)."""
    dm = model.clone(decode=True)
    shapes = jax.eval_shape(
        dm.init, jax.random.PRNGKey(0), jnp.zeros((batch, max_len), jnp.int32)
    )
    return jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"]
    )


def _validate_sampling(temperature: float, top_k, top_p) -> None:
    if (top_k is not None or top_p is not None) and temperature == 0.0:
        raise ValueError("top_k/top_p require temperature > 0 (greedy "
                         "decoding ignores them silently otherwise)")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")


def filtered_logits(logits, temperature: float, top_k, top_p):
    """The sampling distribution as masked/scaled logits: temperature,
    then top-k, then nucleus top-p (the serving convention). greedy
    (temperature == 0) is the caller's branch — this requires T > 0.
    Shared by ancestral sampling (`generate`) and speculative decoding,
    where the SAME filtered distribution must be used for drafting,
    acceptance ratios, and residual sampling for the scheme to be exact."""
    logits = logits / temperature
    rows = jnp.arange(logits.shape[0])[:, None]
    if top_k is not None and top_k < logits.shape[-1]:
        # Rank-exact: exactly top_k survivors even under tied logits
        # (lax.top_k breaks ties deterministically), and no full sort
        # in the per-token decode loop.
        _, idx = jax.lax.top_k(logits, top_k)
        keep = jnp.zeros(logits.shape, bool).at[rows, idx].set(True)
        logits = jnp.where(keep, logits, -jnp.inf)
    if top_p is not None and top_p < 1.0:
        # Nucleus, rank-exact: ONE descending argsort; keep the
        # smallest prefix whose cumulative probability reaches top_p
        # (exclusive prefix sum — the top token always survives), then
        # scatter the rank-space mask back to vocab positions.
        order = jnp.argsort(-logits, axis=-1)
        sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1) - probs  # exclusive prefix sum
        keep = jnp.zeros(logits.shape, bool).at[rows, order].set(
            cum < top_p)
        logits = jnp.where(keep, logits, -jnp.inf)
    return logits


def _prefill(dm, params, cache, prompt, chunk: int | None):
    """Fill the decode cache with the prompt and return (cache, logits of
    the last prompt position).

    The FIRST block always goes through a `prefill=True` clone: an empty
    cache means the block attends only within itself — plain causal
    self-attention — so the model routes it through its configured kernel
    (flash on chip: O(p) score memory, MXU tiles) instead of the s × cap
    masked dense einsum, while still writing the cache. `chunk=None`
    covers the whole prompt that way. A chunk size C additionally scans
    ⌊p/C⌋ C-token blocks (first via the kernel, the rest — which need
    cache context — via the dense step: O(C · cap) scores, or
    O(C · (window + C)) under a windowed model's ring cache) plus one
    remainder block. Chunking changes only the blocking of the same
    block-causal computation, so outputs are identical (parity-tested)."""
    b, p = prompt.shape
    pm = dm.clone(prefill=True)

    def step(m, cache, toks):
        logits, mut = m.apply(
            {"params": params, "cache": cache}, toks, mutable=["cache"])
        return mut["cache"], logits[:, -1, :]

    if chunk is None or chunk >= p:
        return step(pm, cache, prompt)
    if chunk < 1:
        raise ValueError(f"prefill_chunk must be >= 1, got {chunk}")
    k, rem = divmod(p, chunk)
    cache, last_row = step(pm, cache, prompt[:, :chunk])

    def scan_step(carry, toks):
        cache, _ = carry
        cache, row = step(dm, cache, toks)
        # Last row rides the CARRY, not the stacked ys: stacking would
        # hold a (p/C, b, vocab) buffer live through the scan — an
        # O(p)-sized allocation on the path whose purpose is bounding
        # peak memory.
        return (cache, row), None

    if k > 1:
        chunks = prompt[:, chunk:k * chunk].reshape(
            b, k - 1, chunk).swapaxes(0, 1)
        (cache, last_row), _ = jax.lax.scan(
            scan_step, (cache, last_row), chunks)
    if rem:
        cache, last_row = step(dm, cache, prompt[:, k * chunk:])
    return cache, last_row


def make_sampler(temperature: float, top_k, top_p):
    """(logits (b, V), key) -> (b,) int32 tokens: argmax at temperature 0,
    else categorical over the filtered distribution. The ONE sampler both
    `generate` and the BatchServer draw through, so their outputs can't
    diverge in sampling semantics."""

    def sample(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            key, filtered_logits(logits, temperature, top_k, top_p),
            axis=-1).astype(jnp.int32)

    return sample


def generate(
    model,
    params,
    prompt,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    rng=None,
    eos_id: int | None = None,
    prefill_chunk: int | None = None,
):
    """Generate `max_new_tokens` continuations of `prompt` (b, p) int32.

    temperature 0.0 = greedy argmax; otherwise softmax sampling at the
    given temperature (one PRNG key per step, split from `rng`),
    optionally restricted to the `top_k` highest-probability tokens
    and/or the nucleus of cumulative probability `top_p` (both masks
    compose: k first, then p — the common serving convention). After a
    sequence emits `eos_id` every later position is pinned to `eos_id`.
    Returns (b, p + max_new_tokens) int32 — prompt included.

    Jit-friendly: callers can `jax.jit(partial(generate, model),
    static_argnames=("max_new_tokens", "temperature", "top_k", "top_p",
    "prefill_chunk"))`; shapes are static throughout (the sampling knobs
    are trace-time constants baked into the sampler, and prefill_chunk
    sets the prefill scan's block shape, so they must all be static).
    """
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    _validate_sampling(temperature, top_k, top_p)
    b, p = prompt.shape
    dm = model.clone(decode=True)
    cache = init_cache(model, b, p + max_new_tokens)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    sample = make_sampler(temperature, top_k, top_p)

    # Prefill: fill cache[0:p] and take the first next-token distribution
    # from the final prompt position (chunked when prefill_chunk is set —
    # long prompts without O(p^2) score memory).
    cache, last = _prefill(dm, params, cache, prompt, prefill_chunk)
    key0, rng = jax.random.split(rng)
    tok = sample(last, key0)
    done = (tok == eos_id) if eos_id is not None else jnp.zeros((b,), bool)

    def body(carry, key):
        cache, tok, done = carry
        logits, mut = dm.apply(
            {"params": params, "cache": cache}, tok[:, None], mutable=["cache"]
        )
        nxt = sample(logits[:, -1, :], key)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.int32(eos_id), nxt)
            done = done | (nxt == eos_id)
        return (mut["cache"], nxt, done), nxt

    keys = jax.random.split(rng, max_new_tokens - 1)
    _, rest = jax.lax.scan(body, (cache, tok, done), keys)
    return jnp.concatenate(
        [prompt.astype(jnp.int32), tok[:, None]]
        + ([rest.swapaxes(0, 1)] if max_new_tokens > 1 else []),
        axis=1,
    )


def _leading_accepts(accept) -> jnp.ndarray:
    """(b, g) bool -> (b,) count of leading True per row: the number of
    draft tokens accepted before the first rejection."""
    return jnp.cumprod(accept.astype(jnp.int32), axis=1).sum(axis=1)


def _residual_probs(p, q):
    """The rejection-sampling residual norm(max(p - q, 0)): sampling from
    it after rejecting a draft from q makes the combined marginal exactly
    p (speculative decoding's correctness identity:
    q·min(1, p/q) + (1 - Σ min(p, q))·residual = p). Where p == q the
    residual has zero mass (rejection probability is 0, so the branch is
    never taken); fall back to p so categorical stays well-defined under
    vmap/where."""
    r = jnp.maximum(p - q, 0.0)
    z = r.sum(axis=-1, keepdims=True)
    return jnp.where(z > 0, r / jnp.maximum(z, 1e-30), p)



def _make_spec_round_core(tm, dm, params, draft_params, gamma: int,
                          greedy: bool, probs_of, t_ring: bool,
                          d_ring: bool):
    """The DEVICE core of one speculative round — draft scan (gamma+1
    steps), single-forward verify, accept/reject, fix/bonus token,
    committed-block construction, ring stash/restore — shared by
    `speculative_generate` and the speculative BatchServer so the
    exactness machinery cannot fork. Callers supply the two
    schedule-dependent pieces: `adjust_n(n_rows)` turns raw per-row
    acceptance into the commit length (identity for pure per-row;
    done-freeze + batch-min for lockstep), and `commit_index(n_eff)`
    yields the post-round cache index (max_new clamps / capacity parks),
    which the ring restore keys on. The caller sets the cache index and
    derives the next input token from the (possibly eos-pinned) block.

    Returns (t_cache, d_cache, w, n_rows, n_eff) with w (b, gamma+1):
    each row's committed tokens are w[:n_eff+1]."""

    def draft_step(carry, key):
        d_cache, tok = carry
        logits, mut = dm.apply(
            {"params": draft_params, "cache": d_cache}, tok[:, None],
            mutable=["cache"])
        row = logits[:, -1, :]
        if greedy:
            nxt = jnp.argmax(row, axis=-1).astype(jnp.int32)
            q = jax.nn.one_hot(nxt, row.shape[-1], dtype=jnp.float32)
        else:
            q = probs_of(row)
            # where(q > 0, log q, -inf), not log(max(q, eps)): a top-k/p
            # filtered-out token must have EXACTLY zero draw probability,
            # or the scheme's support can leak outside generate()'s.
            nxt = jax.random.categorical(
                key, jnp.where(q > 0, jnp.log(q), -jnp.inf), axis=-1
            ).astype(jnp.int32)
        return (mut["cache"], nxt), (nxt, q)

    def round_core(t_cache, d_cache, last_tok, idx0, k_draft, k_accept,
                   k_fix, adjust_n, commit_index):
        b = last_tok.shape[0]
        rows_i = jnp.arange(b)
        # Both caches sit at idx0 (the round-boundary invariant); ring
        # mode stashes the slots this round overwrites.
        d_stash = (_spec_ring_stash(d_cache, idx0, gamma + 1)
                   if d_ring else None)
        t_stash = (_spec_ring_stash(t_cache, idx0, gamma + 1)
                   if t_ring else None)

        # 1. Draft gamma tokens (small model, sequential scan) — plus ONE
        # extra step whose sampled token is discarded: it exists to feed
        # d_gamma back through the draft so its K/V lands in the draft
        # cache. Without it, a fully-accepted round (n == gamma) leaves
        # the committed frontier's last token MISSING from the draft
        # cache (the draft never consumed its own final sample), and
        # every later round drafts against a zero K/V slot — silently
        # wrong q, collapsing the acceptance rate.
        (d_cache, _), (d_toks, q_probs) = jax.lax.scan(
            draft_step, (d_cache, last_tok),
            jax.random.split(k_draft, gamma + 1))
        d_toks = d_toks.swapaxes(0, 1)[:, :gamma]       # (b, gamma)
        q_probs = q_probs.swapaxes(0, 1)[:, :gamma]     # (b, gamma, V)

        # 2. Verify: ONE target forward over [last, d_1..d_gamma] — row j
        # scores draft position j, row gamma is the bonus distribution.
        block = jnp.concatenate([last_tok[:, None], d_toks], axis=1)
        t_logits, mut = tm.apply(
            {"params": params, "cache": t_cache}, block, mutable=["cache"])
        t_cache = mut["cache"]

        # 3. Accept/reject each draft position against the target.
        p_probs = None
        if greedy:
            t_argmax = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
            accept = d_toks == t_argmax[:, :gamma]
        else:
            p_probs = probs_of(
                t_logits.reshape(b * (gamma + 1), -1)
            ).reshape(b, gamma + 1, -1)
            rows = rows_i[:, None]
            cols = jnp.arange(gamma)[None, :]
            p_tok = p_probs[rows, cols, d_toks]
            q_tok = q_probs[rows, cols, d_toks]
            u = jax.random.uniform(k_accept, (b, gamma))
            accept = u * q_tok < p_tok
        n_rows = _leading_accepts(accept)
        n_eff = adjust_n(n_rows)

        # 4. The (n_eff+1)-th token of the round, per row: its own
        # accepted draft token when its rejection came later (lockstep
        # only — the coin already accepted position n_eff), else the
        # residual sample at its own rejection point (exactness partner
        # of the rejection), else — whole block accepted — a bonus
        # sample from the target's row gamma.
        if greedy:
            fix_tok = t_argmax[rows_i, n_eff]
        else:
            p_n = p_probs[rows_i, n_eff, :]
            q_n = q_probs[
                rows_i, jnp.minimum(n_eff, gamma - 1), :]  # row gamma: unused
            res = _residual_probs(p_n, q_n)
            bonus_or_res = jnp.where((n_eff >= gamma)[:, None], p_n, res)
            fix_tok = jax.random.categorical(
                k_fix,
                jnp.where(bonus_or_res > 0, jnp.log(bonus_or_res),
                          -jnp.inf), axis=-1
            ).astype(jnp.int32)
        keep_own = (n_rows > n_eff) & (n_eff < gamma)
        e_tok = jnp.where(keep_own,
                          d_toks[rows_i, jnp.minimum(n_eff, gamma - 1)],
                          fix_tok).astype(jnp.int32)

        # 5. The committed block (static width; entries past n_eff are
        # junk the caller discards or overwrites).
        w = jnp.concatenate([d_toks, e_tok[:, None]], axis=1)
        offs = jnp.arange(gamma + 1)[None, :]
        w = jnp.where(offs == n_eff[:, None], e_tok[:, None], w)

        # 6. Ring rollback keyed on the caller's committed index.
        new_idx = commit_index(n_eff)
        if t_ring:
            t_cache = _spec_ring_restore(t_cache, t_stash, idx0, new_idx,
                                         gamma + 1)
        if d_ring:
            d_cache = _spec_ring_restore(d_cache, d_stash, idx0, new_idx,
                                         gamma + 1)
        return t_cache, d_cache, w, n_rows, n_eff

    return round_core


def speculative_generate(
    model,
    params,
    draft_model,
    draft_params,
    prompt,
    max_new_tokens: int,
    *,
    gamma: int = 4,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    rng=None,
    eos_id: int | None = None,
    prefill_chunk: int | None = None,
    per_row: bool = False,
    return_stats: bool = False,
):
    """Speculative decoding: draft `gamma` tokens with the cheap
    `draft_model`, verify them all in ONE target forward, keep the
    accepted prefix — exact with respect to the target's sampling
    distribution (greedy output is bitwise `generate`'s; sampled output
    follows the identical per-position distribution via the
    accept/residual rule of `_residual_probs`).

    TPU-first shape discipline: every round runs the same static program —
    gamma single-token draft steps (small-model scan) and one
    (b, gamma+1)-token target verify (MXU-batched, reusing the decode
    cache's block step) — inside a `lax.while_loop`. By default the batch
    commits in LOCKSTEP: n = min over sequences of each row's
    accepted-prefix length, and every sequence advances n+1 tokens (its
    own accepted draft token, or its residual/bonus sample, at position
    n). Truncating at a cross-batch stopping time discards only later
    coin flips, so each row's kept tokens still follow the exact
    per-position scheme; the cost is throughput (min over the batch), not
    correctness. Both KV caches roll back by simply writing
    `cache_index` — entries beyond it are masked by the decode step's
    `key_pos <= q_pos` and overwritten by the next round's block write.

    `per_row=True` removes the lockstep throughput cost: the models run
    with per-row cache indexes (the continuous-batching substrate), so
    EVERY row commits its own full accepted prefix each round — the
    min-over-batch existed only because a scalar cache index forces one
    shared frontier. Rows that reach max_new_tokens early keep
    drafting/verifying garbage into their own (bounded, frozen-frontier)
    cache tail until the slowest row finishes — wasted compute, identical
    outputs; the same static-shape trade the BatchServer makes.

    The draft model trades acceptance rate for speed (same tokenizer/vocab
    required); its quality affects ONLY throughput, never the output
    distribution. Returns (b, p + max_new_tokens) int32 like `generate`;
    with return_stats=True, also a dict with `rounds` and
    `draft_accept_rate` (acceptance over rows still doing real work —
    eos-finished and schedule-frozen rows are excluded; diagnostics for
    tuning gamma).
    """
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    _validate_sampling(temperature, top_k, top_p)
    b, p = prompt.shape
    # Slack past max_new: the verify block overshoots by < gamma, and in
    # per-row mode a finished row's frozen frontier rewrites one more
    # block-width each extra round.
    cap = p + max_new_tokens + gamma + 1
    # Windowed models CAN speculate against the rolling ring cache: the
    # round stashes the <= gamma+1 slots it will overwrite and restores
    # the rejected span after the accept decision (_spec_ring_stash /
    # _spec_ring_restore) — rollback costs O(gamma) per layer, not a ring
    # rebuild. Requires gamma + 1 <= window (otherwise a round's writes
    # would lap the ring and the stash would hold duplicate slots);
    # narrower windows fall back to the full-capacity masked cache, where
    # rollback is just the index rewrite.
    t_ring = _spec_ring_ok(model, gamma)
    d_ring = _spec_ring_ok(draft_model, gamma)
    tm = model.clone(decode=True, per_row_cache=per_row,
                     decode_ring_cache=t_ring)
    dm = draft_model.clone(decode=True, per_row_cache=per_row,
                           decode_ring_cache=d_ring)
    t_cache = init_cache(tm, b, cap)
    d_cache = init_cache(dm, b, cap)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    greedy = temperature == 0.0

    def probs_of(logits):
        return jax.nn.softmax(
            filtered_logits(logits, temperature, top_k, top_p), axis=-1)

    # Prefill both models on the prompt; the first committed token comes
    # from the TARGET (position p is an ordinary target sample — the
    # speculative scheme only covers positions the draft proposed).
    t_cache, last = _prefill(tm, params, t_cache, prompt, prefill_chunk)
    d_cache, _ = _prefill(dm, draft_params, d_cache, prompt, prefill_chunk)
    key0, rng = jax.random.split(rng)
    tok0 = (jnp.argmax(last, axis=-1) if greedy
            else jax.random.categorical(
                key0, filtered_logits(last, temperature, top_k, top_p),
                axis=-1)).astype(jnp.int32)
    done0 = (tok0 == eos_id) if eos_id is not None else jnp.zeros((b,), bool)

    out0 = jnp.zeros((b, cap), jnp.int32)
    out0 = jax.lax.dynamic_update_slice(out0, prompt.astype(jnp.int32), (0, 0))
    out0 = out0.at[:, p].set(tok0)

    rows_i = jnp.arange(b)
    round_core = _make_spec_round_core(tm, dm, params, draft_params, gamma,
                                       greedy, probs_of, t_ring, d_ring)

    def round_body(state):
        out, n_out, t_cache, d_cache, done, rng, rounds, acc_sum, prop_sum = state
        L_rows = p + n_out            # (b,) committed tokens per row
        last_tok = out[rows_i, L_rows - 1]
        rng, k_draft, k_accept, k_fix = jax.random.split(rng, 4)
        idx0 = L_rows - 1  # the round-boundary invariant

        def adjust_n(n_raw):
            # A finished row must not hold the batch back (its output is
            # pinned to eos regardless of what its branch computes); the
            # round's effective commit length is each row's OWN acceptance
            # in per_row mode, the batch min under a shared scalar cache
            # index (one frontier forces one commit length).
            frozen = jnp.where(done, gamma, n_raw)
            return frozen if per_row else jnp.broadcast_to(
                jnp.min(frozen), (b,))

        def commit_index(n_eff):
            # Clamped at the schedule — a finished row's frontier
            # freezes, bounding its garbage tail.
            return p + jnp.minimum(n_out + n_eff + 1, max_new_tokens) - 1

        t_cache, d_cache, w, n_rows, n_eff = round_core(
            t_cache, d_cache, last_tok, idx0, k_draft, k_accept, k_fix,
            adjust_n, commit_index)
        # Diagnostic accounting on the RAW acceptance: only rows still
        # doing real work count, or eos-finished and schedule-frozen rows
        # (forced to gamma / drafting garbage) would inflate the reported
        # acceptance toward 1.0.
        active = (n_out < max_new_tokens) & ~done
        acc_sum = acc_sum + jnp.sum(jnp.where(active, n_rows, 0))
        prop_sum = prop_sum + gamma * jnp.sum(active)


        # Commit the core's block into `out` (static-width write; entries
        # past n_eff+1 are junk the next round — or the final slice —
        # overwrites/drops), with eos pinning threaded through it.
        offs = jnp.arange(gamma + 1)[None, :]
        if eos_id is not None:
            seen = done
            cols_list = []
            for j in range(gamma + 1):
                wj = jnp.where(seen, jnp.int32(eos_id), w[:, j])
                seen = seen | (wj == eos_id)
                cols_list.append(wj)
            w = jnp.stack(cols_list, axis=1)
            committed_mask = offs <= n_eff[:, None]
            done = done | jnp.any((w == eos_id) & committed_mask, axis=1)
        # Per-row scatter (rows sit at different offsets; finished rows'
        # writes land in the slack columns past max_new and are sliced
        # off). mode="drop" guards the clamped-frontier overshoot.
        out = out.at[rows_i[:, None], L_rows[:, None] + offs].set(
            w, mode="drop")

        # Advance each row and roll both caches to the committed
        # frontier (ring restores already happened inside the core,
        # keyed on the same commit_index): correct K/V exists for
        # [0, commit_len - 1); the last committed token enters the caches
        # as the next round's first input. Stale tail entries are masked
        # and later overwritten.
        n_out_new = jnp.minimum(n_out + n_eff + 1, max_new_tokens)
        cidx = p + n_out_new - 1
        if not per_row:
            cidx = cidx[0]  # scalar-cache models need a scalar index
        t_cache = _set_cache_index(t_cache, cidx)
        d_cache = _set_cache_index(d_cache, cidx)
        return (out, n_out_new, t_cache, d_cache, done, rng,
                rounds + 1, acc_sum, prop_sum)

    def round_cond(state):
        return jnp.min(state[1]) < max_new_tokens

    state = (out0, jnp.full((b,), 1, jnp.int32), t_cache, d_cache, done0,
             rng, jnp.int32(0), jnp.int32(0), jnp.int32(0))
    out, n_out, *_, rounds, acc_sum, prop_sum = jax.lax.while_loop(
        round_cond, round_body, state)
    result = jax.lax.slice(out, (0, 0), (b, p + max_new_tokens))
    if not return_stats:
        return result
    return result, {
        "rounds": rounds,
        "draft_accept_rate": acc_sum / jnp.maximum(prop_sum, 1),
    }


def _map_cache_index(cache, fn):
    """Apply `fn` to every cache_index leaf, other leaves untouched — the
    one place that knows how flax names the decode-cache index, shared by
    the rollback (_set_cache_index) and the serve-side idle clamp so the
    leaf-matching can't drift apart."""

    def fix(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        return fn(leaf) if name == "cache_index" else leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


def _kv_leaves(cache):
    """The cached_key/cached_value leaves of a decode cache, in
    tree-flatten order — the ONE ordering contract the serving tier's KV
    shipping relies on: the prefill rank extracts leaf prefixes in this
    order and the decode rank scatters them back in the same order, so the
    flax naming/layout knowledge stays in this module (like
    _map_cache_index). Leaves are (batch, position, kv_heads, head_dim)."""
    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("cached_key", "cached_value"):
            out.append(leaf)
    return out


def _spec_ring_ok(m, gamma: int) -> bool:
    """True when speculative rounds of this gamma can run on the model's
    rolling ring cache: a round writes gamma + 1 positions, which must not
    lap the ring (duplicate slots in the stash scatter). Shared by
    speculative_generate and the speculative BatchServer."""
    return (m.attn_window is not None
            and getattr(m, "decode_ring_cache", True)
            and gamma + 1 <= m.attn_window)


def _get_cache_index(cache):
    """The current cache_index value (first such leaf — every layer
    carries the same one)."""
    for path, leaf in jax.tree_util.tree_flatten_with_path(cache)[0]:
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name == "cache_index":
            return leaf
    raise ValueError("cache has no cache_index leaf")


def _spec_ring_stash(cache, idx0, span):
    """Gather the ring-cache slots a speculative round is about to
    overwrite: slots (idx0 + i) mod W for i < span, per row. The parallel
    tree this returns feeds _spec_ring_restore after the accept decision.
    Non-k/v leaves pass through untouched (cheap references)."""
    rows = jnp.arange(idx0.shape[0])[:, None]

    def fix(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("cached_key", "cached_value"):
            slot = (idx0[:, None] + jnp.arange(span)) % leaf.shape[1]
            return leaf[rows, slot]  # (b, span, kv, dh)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


def _spec_ring_restore(cache, stash, idx0, new_idx, span):
    """Undo a speculative round's ring writes beyond the committed
    frontier: slots whose global position p >= new_idx regain their
    stashed (previous-occupant) content; committed positions keep the
    round's writes — whose evicted predecessors (p - W < new_idx - W) are
    provably outside every future query's window, so the overwrite is
    safe exactly when it is permanent."""
    rows = jnp.arange(idx0.shape[0])[:, None]
    pos = idx0[:, None] + jnp.arange(span)  # (b, span) global positions
    rollback = pos >= new_idx[:, None]

    def fix(path, leaf, saved):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in ("cached_key", "cached_value"):
            slot = pos % leaf.shape[1]
            cur = leaf[rows, slot]
            merged = jnp.where(rollback[..., None, None], saved, cur)
            return leaf.at[rows, slot].set(merged)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache, stash)


def _set_cache_index(cache, idx):
    """Rewrite every layer's cache_index leaf to `idx` — the rollback
    primitive speculative decoding relies on: the decode step masks keys
    at positions > its running index and block-writes from it, so moving
    the index IS the rollback. `idx` may be a scalar (broadcast to every
    leaf shape) or a (b,) vector for per-row caches."""
    return _map_cache_index(
        cache,
        lambda leaf: jnp.broadcast_to(jnp.asarray(idx, leaf.dtype),
                                      leaf.shape))
