"""Autoregressive generation with a per-layer KV cache.

TPU-first inference loop for the Transformer family: one prefill call
scores the whole prompt (MXU-sized matmuls, causal), then a `lax.scan`
decodes token-by-token against the flax "cache" collection that
`SelfAttention(decode=True)` maintains (ring buffers updated with
`dynamic_update_slice` — static shapes, so the whole loop jits and the
per-step executable is reused). GQA models cache only n_kv_heads, so the
cache — the resident that limits batch at inference — shrinks by
n_heads/n_kv_heads.

The reference repo has no inference path at all (it is a transport;
SURVEY §2.3); this is framework capability above it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_cache(model, batch: int, max_len: int):
    """Allocate the decode cache for `batch` sequences of capacity
    `max_len` (prompt + generated). Shapes come from `eval_shape` — no
    second parameter set is materialized and no forward FLOPs run (a real
    init would execute a full (batch, max_len) causal forward, O(max_len²)
    attention memory, just to throw the result away)."""
    dm = model.clone(decode=True)
    shapes = jax.eval_shape(
        dm.init, jax.random.PRNGKey(0), jnp.zeros((batch, max_len), jnp.int32)
    )
    return jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), shapes["cache"]
    )


def generate(
    model,
    params,
    prompt,
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    rng=None,
    eos_id: int | None = None,
):
    """Generate `max_new_tokens` continuations of `prompt` (b, p) int32.

    temperature 0.0 = greedy argmax; otherwise softmax sampling at the
    given temperature (one PRNG key per step, split from `rng`),
    optionally restricted to the `top_k` highest-probability tokens
    and/or the nucleus of cumulative probability `top_p` (both masks
    compose: k first, then p — the common serving convention). After a
    sequence emits `eos_id` every later position is pinned to `eos_id`.
    Returns (b, p + max_new_tokens) int32 — prompt included.

    Jit-friendly: callers can `jax.jit(partial(generate, model),
    static_argnames=("max_new_tokens", "temperature", "top_k", "top_p"))`;
    shapes are static throughout (the sampling knobs are trace-time
    constants baked into the sampler, so they must be static too).
    """
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if (top_k is not None or top_p is not None) and temperature == 0.0:
        raise ValueError("top_k/top_p require temperature > 0 (greedy "
                         "decoding ignores them silently otherwise)")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    b, p = prompt.shape
    dm = model.clone(decode=True)
    cache = init_cache(model, b, p + max_new_tokens)
    if rng is None:
        rng = jax.random.PRNGKey(0)

    def sample(last_logits, key):
        if temperature == 0.0:
            return jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
        logits = last_logits / temperature
        rows = jnp.arange(logits.shape[0])[:, None]
        if top_k is not None and top_k < logits.shape[-1]:
            # Rank-exact: exactly top_k survivors even under tied logits
            # (lax.top_k breaks ties deterministically), and no full sort
            # in the per-token decode loop.
            _, idx = jax.lax.top_k(logits, top_k)
            keep = jnp.zeros(logits.shape, bool).at[rows, idx].set(True)
            logits = jnp.where(keep, logits, -jnp.inf)
        if top_p is not None and top_p < 1.0:
            # Nucleus, rank-exact: ONE descending argsort; keep the
            # smallest prefix whose cumulative probability reaches top_p
            # (exclusive prefix sum — the top token always survives), then
            # scatter the rank-space mask back to vocab positions.
            order = jnp.argsort(-logits, axis=-1)
            sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
            probs = jax.nn.softmax(sorted_logits, axis=-1)
            cum = jnp.cumsum(probs, axis=-1) - probs  # exclusive prefix sum
            keep = jnp.zeros(logits.shape, bool).at[rows, order].set(
                cum < top_p)
            logits = jnp.where(keep, logits, -jnp.inf)
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

    # Prefill: one call over the whole prompt fills cache[0:p] and yields
    # the first next-token distribution from the final prompt position.
    logits, mut = dm.apply(
        {"params": params, "cache": cache}, prompt, mutable=["cache"]
    )
    cache = mut["cache"]
    key0, rng = jax.random.split(rng)
    tok = sample(logits[:, -1, :], key0)
    done = (tok == eos_id) if eos_id is not None else jnp.zeros((b,), bool)

    def body(carry, key):
        cache, tok, done = carry
        logits, mut = dm.apply(
            {"params": params, "cache": cache}, tok[:, None], mutable=["cache"]
        )
        nxt = sample(logits[:, -1, :], key)
        if eos_id is not None:
            nxt = jnp.where(done, jnp.int32(eos_id), nxt)
            done = done | (nxt == eos_id)
        return (mut["cache"], nxt, done), nxt

    keys = jax.random.split(rng, max_new_tokens - 1)
    _, rest = jax.lax.scan(body, (cache, tok, done), keys)
    return jnp.concatenate(
        [prompt.astype(jnp.int32), tok[:, None]]
        + ([rest.swapaxes(0, 1)] if max_new_tokens > 1 else []),
        axis=1,
    )
