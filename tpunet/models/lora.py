"""LoRA adapter utilities: masking, base grafting, merging.

The model side is `Transformer(lora_rank=r)` (every Dense becomes a
`LoraDense`: base under the "base" submodule, `lora_a`/`lora_b`
alongside). These helpers supply the workflow around it:

  graft_base(adapted_init, base_params)  load a trained base checkpoint
      (fp kernels or quantize_params output) into a fresh adapted tree —
      adapters keep their fresh init (B = 0, so the grafted model is
      bitwise the base model before training).
  lora_mask(params)                      pytree of bools, True only on
      lora_a/lora_b (inspection / custom optimizer wiring).
  lora_optimizer(tx, params)             the canonical frozen-base
      optimizer: tx on the adapters, set_to_zero on everything else.
      QLoRA note: differentiate with `jax.value_and_grad(loss, allow_int=
      True)` (the int8 base is inside params; its grads come back as
      float0) and apply with `lora_apply_updates` (plain
      optax.apply_updates can't add float0; the helper treats it as
      "leave the leaf alone").
      (NOT `optax.masked(tx, mask)` alone — masked leaves the unmasked
      updates as RAW GRADIENTS, which apply_updates would add to the
      "frozen" base; the classic footgun this helper exists to bury.)
  merge_lora(params, alpha=None)         fold A @ B · (alpha/r) into each
      fp base kernel and return a PLAIN tree for `Transformer(lora_rank=0)`
      — zero inference overhead once training is done. Quantized bases
      are rejected (int8 + fp delta cannot fold losslessly; keep serving
      the adapted model, which is the QLoRA deployment mode anyway).
"""

from __future__ import annotations

from typing import Mapping

import jax.numpy as jnp
import numpy as np


def _is_lora_node(node) -> bool:
    return isinstance(node, Mapping) and "lora_a" in node and "base" in node


def lora_mask(params):
    """Bool pytree: True exactly on lora_a/lora_b leaves (the trainable
    set for optax.masked / optax.multi_transform)."""

    def walk(node):
        if isinstance(node, Mapping):
            return {k: (True if k in ("lora_a", "lora_b")
                        and not isinstance(v, Mapping) else walk(v))
                    for k, v in node.items()}
        return False

    return walk(params)


def lora_optimizer(tx, params):
    """optax transform training ONLY the adapters: `tx` where lora_mask is
    True, set_to_zero everywhere else (embed, norms, base kernels stay
    bitwise frozen)."""
    import jax
    import optax

    labels = jax.tree.map(lambda m: "train" if m else "freeze",
                          lora_mask(params))
    return optax.multi_transform(
        {"train": tx, "freeze": optax.set_to_zero()}, labels)


def lora_apply_updates(params, updates):
    """optax.apply_updates that passes float0 updates through unchanged —
    the QLoRA apply step for hand-rolled loops. Under `allow_int=True`
    the frozen int8 base's gradients come back as float0 (a zero-size
    dtype no arithmetic accepts), and plain apply_updates crashes adding
    them; a float0 update means "leave the leaf alone", which is exactly
    the frozen-base contract. make_train_step/fit() use the same
    semantics internally, so QLoRA trains through the standard driver
    too."""
    from tpunet.train.trainer import _apply_updates

    return _apply_updates(params, updates)


def graft_base(adapted_init, base_params):
    """Fresh `Transformer(lora_rank=r).init` tree + trained base tree ->
    adapted tree with the base's weights. Wherever the adapted tree has a
    LoraDense node, the base tree holds the corresponding Dense dict at
    the SAME path (minus the "base" nesting); everything else (embed,
    norms) is taken from the base tree directly."""

    def walk(a_node, b_node):
        if _is_lora_node(a_node):
            return {**a_node, "base": b_node}
        if isinstance(a_node, Mapping):
            if not isinstance(b_node, Mapping):
                raise ValueError(
                    f"tree mismatch: adapted node has keys "
                    f"{sorted(a_node)} but base node is a leaf")
            return {k: walk(v, b_node[k]) for k, v in a_node.items()}
        return b_node

    return walk(adapted_init, base_params)


def merge_lora(params, alpha: float | None = None):
    """Adapted tree -> plain tree with A @ B · (alpha/r) folded into each
    base kernel (use with the lora_rank=0 model). The rank is read off
    each node's lora_a (a caller-supplied rank that disagreed with the
    params would silently mis-scale the merge). Pass the SAME alpha the
    model was built with; None means alpha = rank (scale 1), matching
    LoraDense's default. fp bases only."""

    def walk(node):
        if _is_lora_node(node):
            base = node["base"]
            if "kernel" not in base:
                raise ValueError(
                    "merge_lora requires an fp base (int8 bases can't "
                    "absorb an fp delta losslessly) — serve the adapted "
                    "model instead")
            a = np.asarray(node["lora_a"], np.float32)
            b = np.asarray(node["lora_b"], np.float32)
            rank = a.shape[1]
            scale = (alpha if alpha is not None else rank) / rank
            w = np.asarray(base["kernel"], np.float32)
            return {"kernel": jnp.asarray(w + (a @ b) * scale,
                                          base["kernel"].dtype)}
        if isinstance(node, Mapping):
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(params)
