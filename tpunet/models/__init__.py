"""Model zoo for tpunet benchmarks.

The reference's end-to-end benchmark is data-parallel VGG16 synthetic
training (reference: README.md:52-84, 4046 img/s on 32 V100 with the
multi-stream transport vs 2744 baseline); VGG16 is therefore the flagship
model here, built TPU-first in flax (bf16-friendly, MXU-sized matmuls).

The second family is a GPT-style Transformer exercising every parallelism
axis first-class: Megatron TP partition rules, ring attention (in-pod
shard_map/ppermute or cross-host over the DCN transport), and a
Switch-style MoE with expert-parallel sharding.
"""

from tpunet.models.generate import (  # noqa: F401
    generate,
    init_cache,
    speculative_generate,
)
from tpunet.models.lora import (  # noqa: F401
    graft_base,
    lora_apply_updates,
    lora_mask,
    lora_optimizer,
    merge_lora,
)
from tpunet.models.quant import (  # noqa: F401
    dequantize_kernel,
    quantize_params,
)
from tpunet.models.serve import BatchServer  # noqa: F401
from tpunet.models.transformer import (  # noqa: F401
    Transformer,
    transformer_partition_rules,
)
from tpunet.models.vgg import VGG, VGG16, VGG16_CFG, vgg16  # noqa: F401
