"""Transformer (GPT-style decoder) — TPU-first flax implementation.

The second model family of the framework (next to VGG): a causal LM built for
the parallelism layer to exercise every axis the task requires first-class:

  * dp  — batch sharding (gradient all-reduce inserted by XLA / DCN tier)
  * mdl — Megatron tensor parallelism: qkv + mlp-up column-parallel,
    out-proj + mlp-down row-parallel (`transformer_partition_rules`); XLA
    derives the all-reduces from the shardings alone.
  * sp  — sequence/context parallelism: `attn_impl="ring"` routes attention
    through `tpunet.parallel.ring_attention` (shard_map + ppermute ring,
    online softmax) so context length scales with devices.
  * ep  — expert parallelism: optional Switch-style MoE MLP whose expert
    weights carry a leading expert dim to shard over `ep`; the one-hot
    einsum dispatch lets XLA emit the all-to-alls.

Design: pre-norm blocks, RMSNorm, rotary position embeddings (global
positions — computed before the sequence dim is sharded, so ring attention
needs no position bookkeeping), no biases (TP-friendly), f32 params with
configurable compute dtype (bf16 keeps the MXU fed).

The reference repo has no model layer at all (SURVEY §2.3: TP/PP/SP/EP
"absent"); this module is capability the TPU build adds above the transport.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tpunet.ops import attention_reference, flash_attention
from tpunet.parallel.ring_attention import ring_self_attention
from tpunet.parallel.ulysses import ulysses_self_attention


def rotary_embed(x, base: float = 10000.0, pos_offset: int = 0, positions=None):
    """Rotary position embedding. x: (b, s, h, d). pos_offset shifts to
    global positions when x is a sequence shard (cross-host ring attention —
    each process holds positions [offset, offset + s)). `positions`
    overrides with an explicit global-position vector: (s,) shared across
    the batch, or (b, s) per-row — what the per-row decode cache needs,
    where each batch slot sits at its own sequence offset."""
    _, s, _, d = x.shape
    half = d // 2
    freqs = jnp.exp(-math.log(base) * jnp.arange(0, half, dtype=jnp.float32) / half)
    if positions is None:
        positions = pos_offset + jnp.arange(s, dtype=jnp.float32)
    angles = positions.astype(jnp.float32)[..., :, None] * freqs  # (…, s, half)
    if angles.ndim == 2:
        angles = angles[None]  # shared positions -> one broadcast batch row
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    rot = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rot.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],))
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (norm * scale).astype(x.dtype)


class QuantDense(nn.Module):
    """Weight-only int8 Dense: kernel stored int8 with a per-output-channel
    f32 scale (w ≈ q · scale, symmetric absmax). Decode is weight-HBM-
    bandwidth-bound, so halving the kernel bytes is a direct tokens/s
    lever; the dequant is a cast + column scale that XLA fuses around the
    dot, so the int8 tensor is what actually streams from HBM. Params come
    from `tpunet.models.quantize_params` on a trained fp tree — a fresh
    init is a zero skeleton (shape/dtype template only). Inference path;
    int8 params take no gradients."""

    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        q = self.param("q", nn.initializers.zeros,
                       (x.shape[-1], self.features), jnp.int8)
        scale = self.param("scale", nn.initializers.ones,
                           (self.features,), jnp.float32)
        y = x.astype(self.dtype) @ q.astype(self.dtype)
        return y * scale.astype(self.dtype)


class LoraDense(nn.Module):
    """Dense with a rank-r LoRA adapter: y = base(x) + (x @ A) @ B ·
    (alpha/r). B initializes to ZERO, so a freshly-adapted model is
    bitwise the base model; training typically updates only A/B
    (`tpunet.models.lora_optimizer` — NOT bare optax.masked, which passes
    raw gradients through to the "frozen" base) — the base stays frozen,
    which is the parameter-efficient point. `quant=True` puts the base in int8
    (QLoRA-style: frozen quantized weights stream at half bandwidth,
    trainable adapters stay fp). Base params live under the "base"
    submodule with their ordinary leaf names (kernel, or q/scale);
    `tpunet.models.lora.graft_base` maps a base checkpoint /
    quantize_params output into the adapted tree."""

    features: int
    rank: int
    dtype: jnp.dtype = jnp.bfloat16
    alpha: float | None = None  # None -> rank (scale 1)
    quant: bool = False

    @nn.compact
    def __call__(self, x):
        base = (QuantDense(self.features, dtype=self.dtype, name="base")
                if self.quant else
                nn.Dense(self.features, use_bias=False, dtype=self.dtype,
                         name="base"))
        y = base(x)
        a = self.param("lora_a", nn.initializers.normal(0.02),
                       (x.shape[-1], self.rank), jnp.float32)
        bmat = self.param("lora_b", nn.initializers.zeros,
                          (self.rank, self.features), jnp.float32)
        scale = (self.alpha if self.alpha is not None else self.rank
                 ) / self.rank
        delta = (x.astype(self.dtype) @ a.astype(self.dtype)
                 ) @ bmat.astype(self.dtype)
        return y + delta * jnp.asarray(scale, self.dtype)


def _dense(features, dtype, name, weight_quant, lora_rank=0, lora_alpha=None):
    """The Dense factory every matmul in this family goes through: fp by
    default, QuantDense under weight_quant="int8" — SAME module names, so
    the quantized param tree is the fp tree with each kernel dict swapped
    for {q, scale} (what quantize_params produces) — and LoraDense when
    lora_rank > 0 (base params nested under "base", adapters alongside)."""
    if lora_rank > 0:
        return LoraDense(features, lora_rank, dtype=dtype, alpha=lora_alpha,
                         quant=weight_quant is not None, name=name)
    if weight_quant is None:
        return nn.Dense(features, use_bias=False, dtype=dtype, name=name)
    return QuantDense(features, dtype=dtype, name=name)


def _causal_kernel_attention(q, k, v, attn_impl, window, block_q, block_k):
    """The flash/reference causal-attention pair on rotary'd (b, s, heads,
    dh) tensors — ONE dispatch shared by the ordinary forward and the
    kernel-routed prefill, so window handling and the GQA convention can't
    diverge between them: flash consumes kv-head tensors natively; the
    reference einsum gets a (fused) group repeat, a no-op when k/v already
    carry full heads."""
    if attn_impl == "flash":
        return flash_attention(q, k, v, True, block_q=block_q,
                               block_k=block_k, window=window)
    from tpunet.ops.flash_attention import _repeat_kv

    group = q.shape[2] // k.shape[2]
    return attention_reference(q, _repeat_kv(k, group), _repeat_kv(v, group),
                               True, window=window)


class SelfAttention(nn.Module):
    """Causal multi-head self-attention with pluggable impl.

    attn_impl: "reference" (einsum softmax), "flash" (Pallas kernel),
    "zigzag" (balanced causal CP; feed tokens through to_zigzag),
    "ring" / "ulysses" (sequence-parallel attention over `sp_axis` of
    `mesh` — k/v ring rotation vs all-to-all head re-sharding), or
    "dcn_ring" / "dcn_ulysses" / "dcn_zigzag" (sequence sharded across
    PROCESSES over the tpunet DCN transport — requires
    tpunet.distributed.initialize(); dcn_zigzag additionally expects each
    process's shard to be its zigzag chunk pair, i.e. tokens fed through
    to_zigzag, and is the balanced-causal variant of dcn_ring).

    n_kv_heads < n_heads is grouped-query attention: k/v are projected to
    n_kv_heads — the kv projection params/FLOPs and (in decode) the KV
    cache shrink by n_heads/n_kv_heads. The flash impl consumes the
    kv-head tensors natively (in-kernel GQA: K/V stream at 1/group
    bandwidth); every other impl receives a post-rotary broadcast to
    ordinary MHA shapes.

    decode=True switches to autoregressive inference: a "cache" collection
    holds cached_key/cached_value buffers sized by the INIT input's
    sequence length (init with a max-length dummy), and each apply consumes
    the next s tokens (usually 1), attending over the filled prefix.

    attn_window + decode + decode_ring_cache (the default) makes the cache
    a TRUE rolling ring buffer (Mistral-style): leaves are sized
    min(window, capacity), writes land at position mod window, and each
    decode step contracts over window (+ s) entries instead of the full
    capacity — sliding-window attention as a *serving* feature (bounded
    memory, O(window) decode compute), not just a masking pattern.
    """

    n_heads: int
    head_dim: int
    compute_dtype: jnp.dtype = jnp.bfloat16
    attn_impl: str = "reference"
    mesh: Mesh | None = None
    dp_axis: str | None = "dp"
    sp_axis: str = "sp"
    tp_axis: str | None = None
    n_kv_heads: int | None = None
    decode: bool = False
    attn_window: int | None = None  # sliding-window causal (flash/reference)
    # Flash kernel tile sizes (attn_impl="flash" only). 128 matches the MXU/
    # lane width and is the measured round-3 default; expose them so an
    # on-chip block sweep (benchmarks.mfu_attribution --sweep-blocks) can be
    # applied to the model without editing kernel code.
    flash_block_q: int = 128
    flash_block_k: int = 128
    weight_quant: str | None = None
    prefill: bool = False  # decode=True only: first fill of an EMPTY cache
    #   runs block-causal attention through the configured kernel (flash on
    #   chip) instead of the s x cap masked dense einsum below
    per_row_cache: bool = False  # decode=True: cache_index is (b,) — each
    #   batch slot advances independently (continuous batching)
    decode_ring_cache: bool = True  # attn_window + decode: the cache is a
    #   rolling ring buffer — leaves sized min(window, capacity), O(window)
    #   decode contraction. False = full-capacity masked cache.
    #   speculative_generate keeps the ring when gamma + 1 <= window
    #   (rollback stashes/restores the overwritten slots) and falls back
    #   to the masked cache for narrower windows.
    lora_rank: int = 0
    lora_alpha: float | None = None

    @nn.compact
    def __call__(self, x):
        b, s, _ = x.shape
        h, dh = self.n_heads, self.head_dim
        kv = self.n_kv_heads or h
        if h % kv:
            raise ValueError(f"n_heads {h} not divisible by n_kv_heads {kv}")
        if (self.attn_impl == "flash" and not self.decode
                and (self.flash_block_q, self.flash_block_k) != (128, 128)):
            # Explicit (non-default) tile sizes must actually be honored:
            # flash_attention silently falls back to the O(S^2) reference
            # einsum for untileable shapes, and compiled Mosaic silently
            # clamps non-lane-aligned block_q to 128 — either would make a
            # swept "faster" block size a fiction. Fail loud instead.
            # decode=True is exempt: cached steps never reach the flash
            # kernel (dense-einsum branch below) and prefill prompts have
            # arbitrary lengths, where the reference fallback is the point.
            bq, bk = self.flash_block_q, self.flash_block_k
            if s % bq or s % bk or bq % bk:
                raise ValueError(
                    f"flash_block_q/k=({bq},{bk}) do not tile seq {s} under "
                    "the causal kernel (need s%bq==0, s%bk==0, bq%bk==0) — "
                    "flash_attention would silently take the reference path"
                )
            min_sublane = 32 // jnp.dtype(self.compute_dtype).itemsize
            if (bq % 128 and bq != s) or (bk % min_sublane and bk != s):
                raise ValueError(
                    f"flash_block_q/k=({bq},{bk}) are not Mosaic-legal for "
                    f"{jnp.dtype(self.compute_dtype).name} on compiled TPU "
                    f"(block_q: multiple of 128 or full seq; block_k: "
                    f"multiple of {min_sublane}) — the kernel would silently "
                    "clamp them"
                )
        if self.attn_window is not None and self.attn_impl not in (
            "reference", "flash"
        ):
            raise ValueError(
                f"attn_window is only supported by attn_impl 'reference'/"
                f"'flash', not {self.attn_impl!r}"
            )
        dt = self.compute_dtype
        proj = lambda nh, name: _dense(nh * dh, dt, name, self.weight_quant, self.lora_rank, self.lora_alpha)
        q = proj(h, "q")(x).reshape(b, s, h, dh)
        k = proj(kv, "k")(x).reshape(b, s, kv, dh)
        v = proj(kv, "v")(x).reshape(b, s, kv, dh)

        if self.decode:
            # The cached step below is dense local attention — correct for
            # "reference"/"flash" (same math), semantically WRONG for the
            # sequence-parallel impls (sharded/permuted inputs, cross-device
            # k/v). Fail loud rather than generate silent garbage.
            if self.attn_impl not in ("reference", "flash"):
                raise ValueError(
                    f"decode=True does not support attn_impl="
                    f"{self.attn_impl!r}; decode on the full sequence with "
                    "attn_impl='reference' (e.g. model.clone("
                    "attn_impl='reference') before generate())"
                )
            # flax decode-cache pattern: the variables are CREATED on the
            # init call (whose input sets the cache capacity = its seq len)
            # which otherwise runs the ordinary causal path below; every
            # later apply with mutable=["cache"] takes the step branch.
            ring = self.attn_window is not None and self.decode_ring_cache
            # Ring mode sizes the leaves at min(window, capacity) — the
            # init call's s IS the capacity (init with a max-length dummy),
            # so eval_shape-based init_cache allocates O(window) for free.
            cshape = ((b, min(self.attn_window, s), kv, dh) if ring
                      else k.shape)
            filled = self.has_variable("cache", "cached_key")
            ckey = self.variable("cache", "cached_key", jnp.zeros, cshape, k.dtype)
            cval = self.variable("cache", "cached_value", jnp.zeros, cshape, v.dtype)
            cidx = self.variable(
                "cache", "cache_index",
                lambda: jnp.zeros((b,) if self.per_row_cache else (),
                                  jnp.int32)
            )
            if filled:
                idx = cidx.value
                cap = ckey.value.shape[1]
                step_pos = (idx[..., None] + jnp.arange(s)).astype(jnp.float32)
                q = rotary_embed(q, positions=step_pos)
                k = rotary_embed(k, positions=step_pos)
                rows = jnp.arange(b)[:, None]
                if ring:
                    # A full-width ring never overflows: writes land at pos
                    # mod cap and the window mask only addresses the last
                    # `window` positions, all resident. But when the cache
                    # was allocated SMALLER than the window (cap < window),
                    # the ring wraps before the window does — eviction would
                    # silently corrupt in-window history, so keep the loud
                    # NaN-poison past capacity. Both sizes are static.
                    if cap < self.attn_window:
                        overflow = idx + s > cap
                    else:
                        overflow = jnp.zeros(idx.shape, bool)
                    # Attention reads the PRE-write ring (positions < idx)
                    # plus the in-step k/v — exact for s > 1 too, where a
                    # post-write ring would have overwritten entries the
                    # step's earlier queries still see.
                    ring_k, ring_v = ckey.value, cval.value
                    m = min(s, cap)  # static: a step writes its last m
                    wpos = idx[..., None] + jnp.arange(s - m, s)
                    slot = jnp.mod(wpos, cap)  # (m,) or (b, m), all distinct
                    if self.per_row_cache:
                        ckey.value = ckey.value.at[rows, slot].set(k[:, s - m:])
                        cval.value = cval.value.at[rows, slot].set(v[:, s - m:])
                    else:
                        ckey.value = ckey.value.at[:, slot].set(k[:, s - m:])
                        cval.value = cval.value.at[:, slot].set(v[:, s - m:])
                else:
                    # Past-capacity steps would clamp the write start and
                    # silently corrupt the tail; idx is traced, so the
                    # jit-compatible hard failure is poisoning the output to
                    # NaN the moment idx + s overflows — loud at the first
                    # sample. Per-row mode: everything here is (b,)-shaped —
                    # each batch slot sits at its own sequence offset
                    # (continuous batching), overflow poisons only its own
                    # row, and the cache write is a per-row scatter instead
                    # of one slice.
                    overflow = idx + s > cap
                    if self.per_row_cache:
                        pos_i = idx[:, None] + jnp.arange(s)  # (b, s)
                        ckey.value = ckey.value.at[rows, pos_i].set(k)
                        cval.value = cval.value.at[rows, pos_i].set(v)
                    else:
                        ckey.value = jax.lax.dynamic_update_slice(
                            ckey.value, k, (0, idx, 0, 0)
                        )
                        cval.value = jax.lax.dynamic_update_slice(
                            cval.value, v, (0, idx, 0, 0)
                        )
                cidx.value = idx + s
                if self.prefill:
                    # First fill of an EMPTY cache: the block attends only
                    # within itself, which is plain causal self-attention —
                    # run it through the configured kernel (flash: O(s)
                    # memory, MXU-tiled; untileable prompt lengths fall
                    # back to the reference einsum over s x s, still
                    # smaller than the s x cap masked dense below). The
                    # cache write above is all decode needs later. Only
                    # valid at idx == 0 — poisoned to NaN otherwise, same
                    # discipline as the overflow guard.
                    o = _causal_kernel_attention(
                        q, k, v, self.attn_impl, self.attn_window,
                        self.flash_block_q, self.flash_block_k)
                    bad = overflow | (idx != 0)
                    if self.per_row_cache:
                        bad = bad[:, None, None, None]  # poison own row only
                    o = jnp.where(bad, jnp.nan, o).astype(dt)
                    o = o.reshape(b, s, h * dh)
                    return _dense(x.shape[-1], dt, "out", self.weight_quant,
                                  self.lora_rank, self.lora_alpha)(o)
                # Grouped einsum: q reshaped to (b, s, kv, group, dh)
                # contracts DIRECTLY against the (b, K, kv, dh) cache —
                # the group-repeated K/V never exists in HBM. This is the
                # point of GQA at decode time: the cache read per step is
                # kv/h of the MHA equivalent, and materializing a repeat
                # would hand that bandwidth win straight back.
                if ring:
                    # Contract over [pre-write ring | in-step k/v]:
                    # K = window + s entries, not the full capacity. Ring
                    # slot j's global position is the largest p < idx with
                    # p ≡ j (mod cap); p < 0 means never written (or the
                    # previous occupant of a recycled serve slot — idx was
                    # reset, so stale entries are unaddressable by
                    # construction).
                    att_k = jnp.concatenate([ring_k, k], axis=1)
                    att_v = jnp.concatenate([ring_v, v], axis=1)
                    j = jnp.arange(cap)
                    p_ring = (idx[..., None] - 1
                              - jnp.mod(idx[..., None] - 1 - j, cap))
                    p_step = idx[..., None] + jnp.arange(s)
                    key_pos = jnp.concatenate(
                        [jnp.broadcast_to(p_ring, idx.shape + (cap,)),
                         jnp.broadcast_to(p_step, idx.shape + (s,))],
                        axis=-1)  # (K,) or (b, K)
                else:
                    att_k, att_v = ckey.value, cval.value
                    key_pos = jnp.arange(cap)
                qg = q.reshape(b, s, kv, h // kv, dh).astype(jnp.float32)
                # (b, kv, group, s, K) scores; mask to keys at valid global
                # positions <= each query's position (and in-window).
                scores = jnp.einsum(
                    "bqhgd,bkhd->bhgqk", qg, att_k.astype(jnp.float32)
                ) / math.sqrt(dh)
                kp = (key_pos[:, None, None, None, :] if key_pos.ndim == 2
                      else key_pos[None, None, None, None, :])
                pos = idx[..., None] + jnp.arange(s)  # (s,) or (b, s)
                if self.per_row_cache:
                    q_pos = pos[:, None, None, :, None]
                    row_overflow = overflow[:, None, None, None]
                else:
                    q_pos = pos[None, None, None, :, None]
                    row_overflow = overflow
                keep = (kp >= 0) & (kp <= q_pos)
                if self.attn_window is not None:
                    keep &= (q_pos - kp) < self.attn_window
                scores = jnp.where(keep, scores, -jnp.inf)
                probs = jax.nn.softmax(scores, axis=-1)
                o = jnp.einsum(
                    "bhgqk,bkhd->bqhgd", probs, att_v.astype(jnp.float32)
                ).reshape(b, s, h, dh)
                o = jnp.where(row_overflow, jnp.nan, o)
                o = o.astype(dt).reshape(b, s, h * dh)
                return _dense(x.shape[-1], dt, "out", self.weight_quant,
                              self.lora_rank, self.lora_alpha)(o)

        pos_offset = 0
        positions = None
        if self.attn_impl in ("dcn_ring", "dcn_ulysses"):
            # The per-process model sees only its sequence shard; rotary
            # must use global positions for the ring to be coherent.
            from tpunet import distributed

            pos_offset = distributed.rank() * s
        elif self.attn_impl == "dcn_zigzag":
            # Per-process shard = zigzag chunk pair of the global sequence.
            from tpunet import distributed
            from tpunet.parallel.zigzag_attention import zigzag_positions

            positions = zigzag_positions(
                distributed.world_size(),
                distributed.world_size() * s,
                distributed.rank(),
            ).astype(jnp.float32)
        elif self.attn_impl == "zigzag":
            # The WHOLE sequence axis is in zigzag chunk order (tokens fed
            # through to_zigzag); rotary needs each row's natural position.
            from tpunet.parallel.zigzag_attention import to_zigzag

            if self.mesh is None:
                raise ValueError("attn_impl='zigzag' requires a mesh")
            positions = to_zigzag(
                jnp.arange(s, dtype=jnp.float32),
                self.mesh.shape[self.sp_axis], axis=0,
            )
        q = rotary_embed(q, pos_offset=pos_offset, positions=positions)
        k = rotary_embed(k, pos_offset=pos_offset, positions=positions)
        if kv != h and self.attn_impl != "flash":
            # GQA broadcast AFTER rotary (rotary runs on the kv heads): the
            # projection savings are already banked; every impl below then
            # sees plain MHA shapes. XLA fuses the repeat into the consumer.
            # The flash kernel is EXCLUDED: it consumes kv-head tensors
            # natively (per-head BlockSpec index_map), streaming K/V at
            # 1/group the HBM bandwidth instead of reading a repeat.
            k = jnp.repeat(k, h // kv, axis=2)
            v = jnp.repeat(v, h // kv, axis=2)

        if self.attn_impl == "zigzag":
            from tpunet.parallel.zigzag_attention import zigzag_self_attention

            o = zigzag_self_attention(
                q, k, v, self.mesh,
                dp_axis=self.dp_axis, sp_axis=self.sp_axis, tp_axis=self.tp_axis,
            )
        elif self.attn_impl in ("ring", "ulysses"):
            if self.mesh is None:
                raise ValueError(f"attn_impl={self.attn_impl!r} requires a mesh")
            sp_fn = ring_self_attention if self.attn_impl == "ring" else ulysses_self_attention
            o = sp_fn(
                q, k, v, self.mesh, causal=True,
                dp_axis=self.dp_axis, sp_axis=self.sp_axis, tp_axis=self.tp_axis,
            )
        elif self.attn_impl == "dcn_ring":
            from tpunet.parallel.dcn_ring_attention import dcn_ring_attention

            o = dcn_ring_attention(q, k, v, causal=True)
        elif self.attn_impl == "dcn_zigzag":
            from tpunet.parallel.dcn_ring_attention import dcn_zigzag_attention

            o = dcn_zigzag_attention(q, k, v)
        elif self.attn_impl == "dcn_ulysses":
            from tpunet.parallel.ulysses import dcn_ulysses_attention

            o = dcn_ulysses_attention(q, k, v, causal=True)
        else:  # flash / reference — k/v are pre-broadcast for non-flash
            o = _causal_kernel_attention(
                q, k, v, self.attn_impl, self.attn_window,
                self.flash_block_q, self.flash_block_k)

        o = o.reshape(b, s, h * dh)
        return _dense(x.shape[-1], dt, "out", self.weight_quant,
                      self.lora_rank, self.lora_alpha)(o)


class Mlp(nn.Module):
    """Dense MLP: "gelu" (up→gelu→down) or "swiglu" (silu(gate)·up→down,
    the LLaMA-family FFN). Both keep every kernel bias-free and 2-D so the
    Megatron TP rules (up/gate column-parallel, down row-parallel) apply."""

    d_ff: int
    compute_dtype: jnp.dtype = jnp.bfloat16
    mlp_impl: str = "gelu"
    weight_quant: str | None = None
    lora_rank: int = 0
    lora_alpha: float | None = None

    @nn.compact
    def __call__(self, x):
        dt = self.compute_dtype
        wq, lr, la = self.weight_quant, self.lora_rank, self.lora_alpha
        if self.mlp_impl == "swiglu":
            g = _dense(self.d_ff, dt, "gate", wq, lr, la)(x)
            h = _dense(self.d_ff, dt, "up", wq, lr, la)(x)
            h = nn.silu(g) * h
        elif self.mlp_impl == "gelu":
            h = _dense(self.d_ff, dt, "up", wq, lr, la)(x)
            h = nn.gelu(h)
        else:
            raise ValueError(f"unknown mlp_impl {self.mlp_impl!r}")
        return _dense(x.shape[-1], dt, "down", wq, lr, la)(h)


class MoeMlp(nn.Module):
    """Top-k MoE with capacity-bounded one-hot einsum dispatch (top_k=1 is
    Switch routing — the default; top_k=2 is the GShard/Mixtral family).

    Expert weights carry a leading expert dim — shard it over the `ep` mesh
    axis (`transformer_partition_rules`) and XLA turns the dispatch/combine
    einsums into all-to-alls. Tokens over capacity are dropped (residual
    passes them through unchanged), the standard Switch behavior; capacity
    scales with top_k (cap = ceil(k·t/e · capacity_factor)) and slots are
    granted choice-major, so a token's SECONDARY expert overflowing can
    never evict another token's primary assignment. Combine weights are the
    chosen probs (top_k=1, Switch) or the probs renormalized over the
    chosen set (top_k>1, Mixtral convention). The router load-balancing
    loss — primary-assignment fractions, reducing to the Switch formula at
    k=1 — is sown under `intermediates/moe_aux_loss`.
    """

    n_experts: int
    d_ff: int
    capacity_factor: float = 1.25
    compute_dtype: jnp.dtype = jnp.bfloat16
    top_k: int = 1

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        e, f, dt = self.n_experts, self.d_ff, self.compute_dtype
        k = self.top_k
        if not 1 <= k <= e:
            raise ValueError(f"top_k {k} outside [1, n_experts={e}]")
        t = b * s
        cap = max(1, int(math.ceil(k * t / e * self.capacity_factor)))

        wg = self.param("router", nn.initializers.lecun_normal(), (d, e))
        wi = self.param("wi", nn.initializers.lecun_normal(), (e, d, f))
        wo = self.param("wo", nn.initializers.lecun_normal(), (e, f, d))

        xt = x.reshape(t, d)
        logits = jnp.einsum("td,de->te", xt.astype(jnp.float32), wg.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)
        gates, experts = jax.lax.top_k(probs, k)  # (t, k) each, best first
        if k > 1:
            gates = gates / jnp.sum(gates, axis=-1, keepdims=True)
        onehot = jax.nn.one_hot(experts, e, dtype=jnp.float32)  # (t, k, e)

        # Load-balancing aux loss over the PRIMARY assignment:
        # e * sum_e(frac_tokens * frac_prob) — the Switch formula at k=1.
        frac_tokens = jnp.mean(onehot[:, 0, :], axis=0)
        frac_probs = jnp.mean(probs, axis=0)
        self.sow("intermediates", "moe_aux_loss", e * jnp.sum(frac_tokens * frac_probs))

        # Position of each (token, choice) within its expert's capacity
        # buffer. The cumsum runs CHOICE-MAJOR (all choice-0 rows before any
        # choice-1 row): primary assignments claim slots first.
        oh_flat = onehot.transpose(1, 0, 2).reshape(k * t, e)
        pos = jnp.cumsum(oh_flat, axis=0) * oh_flat        # 1-based
        keep = (pos > 0) & (pos <= cap)
        slot = jnp.clip(pos - 1, 0, cap - 1).astype(jnp.int32)
        slot_oh = jax.nn.one_hot(
            jnp.sum(slot * oh_flat.astype(jnp.int32), axis=-1), cap,
            dtype=jnp.float32)
        dispatch = ((oh_flat * keep)[:, :, None] * slot_oh[:, None, :]
                    ).reshape(k, t, e, cap).transpose(1, 0, 2, 3)  # (t,k,e,c)

        xe = jnp.einsum("tkec,td->ecd", dispatch.astype(dt), xt.astype(dt))
        hdn = nn.gelu(jnp.einsum("ecd,edf->ecf", xe, wi.astype(dt)))
        ye = jnp.einsum("ecf,efd->ecd", hdn, wo.astype(dt))
        # Combine weighted by each choice's gate; dropped (over-capacity)
        # choices contribute nothing, matching the dispatch side.
        combine = dispatch * gates[:, :, None, None].astype(dispatch.dtype)
        yt = jnp.einsum("tkec,ecd->td", combine.astype(dt), ye)
        return yt.reshape(b, s, d)


class Block(nn.Module):
    n_heads: int
    head_dim: int
    d_ff: int
    n_experts: int = 0
    capacity_factor: float = 1.25
    compute_dtype: jnp.dtype = jnp.bfloat16
    attn_impl: str = "reference"
    mesh: Mesh | None = None
    dp_axis: str | None = "dp"
    sp_axis: str = "sp"
    tp_axis: str | None = None
    n_kv_heads: int | None = None
    mlp_impl: str = "gelu"
    decode: bool = False
    attn_window: int | None = None
    flash_block_q: int = 128
    flash_block_k: int = 128
    moe_top_k: int = 1
    weight_quant: str | None = None
    prefill: bool = False
    per_row_cache: bool = False
    decode_ring_cache: bool = True
    lora_rank: int = 0
    lora_alpha: float | None = None

    @nn.compact
    def __call__(self, x):
        x = x + SelfAttention(
            self.n_heads, self.head_dim, self.compute_dtype, self.attn_impl,
            self.mesh, self.dp_axis, self.sp_axis, self.tp_axis,
            n_kv_heads=self.n_kv_heads, decode=self.decode,
            attn_window=self.attn_window,
            flash_block_q=self.flash_block_q,
            flash_block_k=self.flash_block_k,
            weight_quant=self.weight_quant, prefill=self.prefill,
            per_row_cache=self.per_row_cache,
            decode_ring_cache=self.decode_ring_cache,
            lora_rank=self.lora_rank,
            lora_alpha=self.lora_alpha, name="attn",
        )(RMSNorm(name="norm1")(x))
        if self.n_experts > 0:
            mlp = MoeMlp(self.n_experts, self.d_ff, self.capacity_factor,
                         self.compute_dtype, top_k=self.moe_top_k, name="moe")
        else:
            mlp = Mlp(self.d_ff, self.compute_dtype, self.mlp_impl,
                      weight_quant=self.weight_quant,
                      lora_rank=self.lora_rank, lora_alpha=self.lora_alpha,
                      name="mlp")
        return x + mlp(RMSNorm(name="norm2")(x))


class Transformer(nn.Module):
    """Causal decoder-only LM. Tokens (b, s) int32 -> logits (b, s, vocab) f32."""

    vocab: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    d_ff: int = 2048
    n_experts: int = 0            # 0 = dense MLP in every block
    moe_every: int = 2            # every k-th block is MoE (when n_experts>0)
    moe_top_k: int = 1            # experts per token: 1 = Switch, 2 = GShard/Mixtral
    capacity_factor: float = 1.25
    compute_dtype: jnp.dtype = jnp.bfloat16
    remat: bool = False           # rematerialize blocks: trade FLOPs for HBM
    remat_policy: str | None = None  # None=save nothing; "dots" saves matmul
    #   outputs (recompute only cheap elementwise — less HBM relief, near-zero
    #   recompute FLOPs); "dots_no_batch" saves weight-stationary dots only.
    attn_impl: str = "reference"
    mesh: Mesh | None = None
    dp_axis: str | None = "dp"
    sp_axis: str = "sp"
    tp_axis: str | None = None
    n_kv_heads: int | None = None  # < n_heads = grouped-query attention
    mlp_impl: str = "gelu"         # "swiglu" = LLaMA-family FFN
    decode: bool = False           # KV-cache autoregressive inference mode
    attn_window: int | None = None  # sliding-window causal attention (Mistral
    #   -style): each token sees the window most recent positions; flash
    #   kernels prune to O(S*window) FLOPs. reference/flash impls only.
    flash_block_q: int = 128       # flash kernel tile sizes; sweep with
    flash_block_k: int = 128       #   benchmarks.mfu_attribution --sweep-blocks
    weight_quant: str | None = None  # "int8" = weight-only quantized matmuls
    #   (inference: pair with tpunet.models.quantize_params on a trained
    #   fp tree; halves the weight HBM traffic decode is bound by)
    prefill: bool = False          # decode=True: route the FIRST cache fill
    #   through the configured attention kernel (flash: O(s) memory, MXU
    #   tiles) instead of the s x cap masked dense einsum; generate() uses a
    #   prefill clone for the whole-prompt call automatically
    per_row_cache: bool = False    # decode=True: per-slot (b,) cache index —
    #   the continuous-batching substrate (tpunet.models.serve.BatchServer)
    decode_ring_cache: bool = True  # attn_window + decode: rolling ring-
    #   buffer KV cache, leaves sized min(window, cap) — bounded memory and
    #   O(window) decode contraction. speculative_generate keeps it when
    #   gamma + 1 <= window (stash/restore rollback), else masked cache.
    lora_rank: int = 0             # > 0: rank-r LoRA adapters on every Dense
    #   (tpunet.models.lora: lora_mask to train only A/B, graft_base to
    #   load a base checkpoint, merge_lora to fold back); composes with
    #   weight_quant="int8" (QLoRA: frozen int8 base + fp adapters)
    lora_alpha: float | None = None

    @nn.compact
    def __call__(self, tokens, train: bool = False, features_only: bool = False):
        # features_only: return the final normed hidden states (b, s, d) in
        # compute_dtype instead of logits — the input the blockwise fused
        # cross-entropy (tpunet.ops.blockwise_cross_entropy) pairs with the
        # lm_head kernel so the (b, s, vocab) logits are never materialized.
        del train  # no dropout in this family; kept for trainer signature
        if self.weight_quant not in (None, "int8"):
            raise ValueError(f"unknown weight_quant {self.weight_quant!r}")
        if self.weight_quant is not None:
            if self.n_experts > 0:
                raise ValueError(
                    "weight_quant does not cover MoE expert einsum weights; "
                    "use a dense model or weight_quant=None")
            if features_only:
                raise ValueError(
                    "weight_quant is incompatible with features_only: the "
                    "blockwise fused cross-entropy reads an fp lm_head "
                    "kernel from the params tree")
        if self.lora_rank > 0 and features_only:
            raise ValueError(
                "lora_rank is incompatible with features_only: the "
                "blockwise fused cross-entropy reads params['lm_head']"
                "['kernel'], but the adapted tree nests it under 'base' "
                "(and the lm_head adapters would be silently dropped) - "
                "merge_lora first, or train without fused xent")
        emb = self.param(
            "embed", nn.initializers.normal(0.02), (self.vocab, self.d_model)
        )
        x = emb[tokens].astype(self.compute_dtype)
        head_dim = self.d_model // self.n_heads
        # remat drops block activations in the forward pass and recomputes
        # them in the backward — the standard long-context memory lever
        # (sequence activations dominate HBM; FLOPs are MXU-cheap).
        policies = {
            None: None,
            "dots": jax.checkpoint_policies.dots_saveable,
            "dots_no_batch":
                jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        }
        if self.remat_policy not in policies:
            # Validated even when remat is off / decoding — a typo'd policy
            # silently doing nothing would corrupt memory-sweep conclusions.
            raise ValueError(f"unknown remat_policy {self.remat_policy!r}")
        if self.decode or not self.remat:
            block_cls = Block
        else:
            pol = policies[self.remat_policy]
            block_cls = nn.remat(Block, policy=pol) if pol else nn.remat(Block)
        for i in range(self.n_layers):
            moe = self.n_experts > 0 and (i + 1) % self.moe_every == 0
            x = block_cls(
                self.n_heads, head_dim, self.d_ff,
                n_experts=self.n_experts if moe else 0,
                capacity_factor=self.capacity_factor,
                moe_top_k=self.moe_top_k,
                compute_dtype=self.compute_dtype, attn_impl=self.attn_impl,
                mesh=self.mesh, dp_axis=self.dp_axis, sp_axis=self.sp_axis,
                tp_axis=self.tp_axis, n_kv_heads=self.n_kv_heads,
                mlp_impl=self.mlp_impl, decode=self.decode,
                attn_window=self.attn_window,
                flash_block_q=self.flash_block_q,
                flash_block_k=self.flash_block_k,
                weight_quant=self.weight_quant, prefill=self.prefill,
                per_row_cache=self.per_row_cache,
                decode_ring_cache=self.decode_ring_cache,
                lora_rank=self.lora_rank, lora_alpha=self.lora_alpha,
                name=f"block{i}",
            )(x)
        x = RMSNorm(name="norm_f")(x)
        if features_only:
            if self.is_initializing():
                # The lm_head param must still exist (fused-xent callers
                # read it from the params tree): materialize the kernel with
                # a 1-token touch instead of the full matmul.
                nn.Dense(self.vocab, use_bias=False, dtype=self.compute_dtype,
                         name="lm_head")(x[..., :1, :])
            return x.astype(self.compute_dtype)
        logits = _dense(self.vocab, self.compute_dtype, "lm_head",
                        self.weight_quant, self.lora_rank,
                        self.lora_alpha)(x)
        return logits.astype(jnp.float32)


def transformer_partition_rules(
    tp_axis: str | None = "mdl", ep_axis: str | None = None
) -> list[tuple[str, P]]:
    """Path-regex → PartitionSpec rules (first match wins; no match =
    replicated). Megatron TP over `tp_axis` (None = no TP); MoE experts over
    `ep_axis` (None = experts replicated)."""
    ep = ep_axis
    return [
        (r".*attn/(q|k|v)/kernel", P(None, tp_axis)),
        (r".*attn/out/kernel", P(tp_axis, None)),
        (r".*mlp/(up|gate)/kernel", P(None, tp_axis)),
        (r".*mlp/down/kernel", P(tp_axis, None)),
        (r".*moe/router", P()),
        (r".*moe/wi", P(ep, None, tp_axis)),
        (r".*moe/wo", P(ep, tp_axis, None)),
        (r".*embed", P(tp_axis, None)),
        (r".*lm_head/kernel", P(None, tp_axis)),
        # weight_quant="int8" trees: q shards exactly like its kernel; the
        # per-output-channel scale shards with the OUTPUT dim — along
        # tp_axis for column-parallel kernels, replicated for row-parallel
        # ones (whose output dim is unsharded). Correctness under TP is
        # free either way: the scale is per-column, so it distributes over
        # the row-parallel psum — (Σ_p x_p @ q_p) · s == Σ_p (x_p @ q_p · s).
        (r".*attn/(q|k|v)/q", P(None, tp_axis)),
        (r".*attn/(q|k|v)/scale", P(tp_axis)),
        (r".*attn/out/q", P(tp_axis, None)),
        (r".*attn/out/scale", P()),
        (r".*mlp/(up|gate)/q", P(None, tp_axis)),
        (r".*mlp/(up|gate)/scale", P(tp_axis)),
        (r".*mlp/down/q", P(tp_axis, None)),
        (r".*mlp/down/scale", P()),
        (r".*lm_head/q", P(None, tp_axis)),
        (r".*lm_head/scale", P(tp_axis)),
        # lora_rank>0 trees: base kernels nest one level deeper ("base/"),
        # same specs as their plain forms. Adapters follow the Megatron
        # LoRA convention: for a column-parallel W, A (in, r) replicates
        # and B (r, out) shards its output dim; for a row-parallel W,
        # A (in, r) shards its input dim and B replicates - each adapter
        # matmul then lives on the same shards as its base matmul.
        (r".*attn/(q|k|v)/base/kernel", P(None, tp_axis)),
        (r".*attn/out/base/kernel", P(tp_axis, None)),
        (r".*mlp/(up|gate)/base/kernel", P(None, tp_axis)),
        (r".*mlp/down/base/kernel", P(tp_axis, None)),
        (r".*lm_head/base/kernel", P(None, tp_axis)),
        (r".*attn/(q|k|v)/base/q", P(None, tp_axis)),
        (r".*attn/(q|k|v)/base/scale", P(tp_axis)),
        (r".*attn/out/base/q", P(tp_axis, None)),
        (r".*attn/out/base/scale", P()),
        (r".*mlp/(up|gate)/base/q", P(None, tp_axis)),
        (r".*mlp/(up|gate)/base/scale", P(tp_axis)),
        (r".*mlp/down/base/q", P(tp_axis, None)),
        (r".*mlp/down/base/scale", P()),
        (r".*lm_head/base/q", P(None, tp_axis)),
        (r".*lm_head/base/scale", P(tp_axis)),
        (r".*(attn/(q|k|v)|mlp/(up|gate)|lm_head)/lora_a", P()),
        (r".*(attn/(q|k|v)|mlp/(up|gate)|lm_head)/lora_b", P(None, tp_axis)),
        (r".*(attn/out|mlp/down)/lora_a", P(tp_axis, None)),
        (r".*(attn/out|mlp/down)/lora_b", P()),
    ]
