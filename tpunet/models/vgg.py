"""VGG — TPU-first flax implementation.

Mirrors the capability of the reference's benchmark workload (Bagua's
synthetic_benchmark.py VGG16, reference README.md:52) without copying any
torch code: conv stacks run in NHWC (TPU-native layout), compute dtype is
configurable (bfloat16 by default for the MXU — params stay f32), and the
classifier is expressed as two large matmuls that tensor-parallel sharding
can split over the `mdl` mesh axis (column- then row-parallel, the
Megatron pattern — XLA inserts the collectives from the shardings).
"""

from __future__ import annotations

from collections.abc import Sequence

import flax.linen as nn
import jax.numpy as jnp

# Channel plan per block; "M" = 2x2 max-pool. The classic 16-layer config.
VGG16_CFG: tuple = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M", 512, 512, 512, "M")


class VGG(nn.Module):
    """VGG-style conv net.

    Attributes:
      cfg: channel plan (ints = 3x3 conv channels, "M" = maxpool).
      num_classes: classifier output size.
      width_mult: scales every channel count (tiny configs for tests).
      hidden: classifier hidden width (4096 in the paper config).
      compute_dtype: activations/matmul dtype (bf16 keeps the MXU fed;
        params remain float32 and XLA casts per-op).
      classifier_dropout: train-mode dropout rate in the head.
    """

    cfg: Sequence = VGG16_CFG
    num_classes: int = 1000
    width_mult: float = 1.0
    hidden: int = 4096
    compute_dtype: jnp.dtype = jnp.bfloat16
    classifier_dropout: float = 0.5

    def _width(self, c: int) -> int:
        return max(8, int(c * self.width_mult)) if self.width_mult != 1.0 else c

    @nn.compact
    def __call__(self, x, train: bool = False):
        """x: NHWC images. Returns (batch, num_classes) float32 logits."""
        dt = self.compute_dtype
        x = x.astype(dt)
        conv_i = 0
        for item in self.cfg:
            if item == "M":
                x = nn.max_pool(x, window_shape=(2, 2), strides=(2, 2))
            else:
                x = nn.Conv(self._width(item), kernel_size=(3, 3), padding=1, dtype=dt,
                            name=f"conv{conv_i}")(x)
                x = nn.relu(x)
                conv_i += 1
        x = x.reshape((x.shape[0], -1))  # flatten
        hidden = self._width(self.hidden)
        # Two big matmuls: fc1 column-parallel / fc2 row-parallel under TP.
        x = nn.Dense(hidden, dtype=dt, name="fc1")(x)
        x = nn.relu(x)
        x = nn.Dropout(self.classifier_dropout, deterministic=not train)(x)
        x = nn.Dense(hidden, dtype=dt, name="fc2")(x)
        x = nn.relu(x)
        x = nn.Dropout(self.classifier_dropout, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=dt, name="head")(x)
        return x.astype(jnp.float32)


def vgg16(num_classes: int = 1000, width_mult: float = 1.0,
          compute_dtype=jnp.bfloat16) -> VGG:
    return VGG(cfg=VGG16_CFG, num_classes=num_classes, width_mult=width_mult,
               compute_dtype=compute_dtype)


VGG16 = vgg16  # alias
