"""Post-training weight-only int8 quantization for the Transformer family.

`quantize_params(params)` converts a trained fp param tree into the tree a
`Transformer(weight_quant="int8")` clone consumes: every Dense kernel dict
{"kernel": (in, out)} becomes {"q": int8 (in, out), "scale": f32 (out,)}
with symmetric per-output-channel absmax scaling (w ≈ q · scale,
q ∈ [-127, 127]). Everything that is not a Dense kernel — the embedding
table, RMSNorm scales — passes through untouched; the module names are
identical, so the swap is purely at the leaf level.

Why weight-only, and why per-output-channel: decode streams every weight
matrix from HBM once per token while activations stay tiny, so weights are
the bandwidth bill — int8 halves it vs bf16 without touching the
activation path's numerics. Per-output-channel scales cost (out,) f32 —
noise next to the kernel — and cut quantization error by the column
dynamic range, and because the scale is per-COLUMN it commutes with the
matmul: x @ (q·scale) == (x @ q) · scale, which is exactly how QuantDense
applies it (the int8 tensor is what streams; the dequant is a fused cast).

Scope: composes with Megatron TP — `transformer_partition_rules` shards
`q` exactly like its kernel and the per-column `scale` with the output
dim (the scale distributes over the row-parallel psum, so sharded and
single-replica runs agree to all-reduce reassociation noise; parity test
on the virtual mesh). The MoE expert einsum weights are not covered —
`Transformer(weight_quant=...)` rejects MoE configs loudly.
"""

from __future__ import annotations

from typing import Mapping

import jax.numpy as jnp
import numpy as np


def quantize_kernel(w) -> dict:
    """One (in, out) fp kernel -> {"q": int8, "scale": f32 (out,)}."""
    w = np.asarray(w, np.float32)
    if w.ndim != 2:
        raise ValueError(f"expected a 2-D kernel, got shape {w.shape}")
    absmax = np.abs(w).max(axis=0)
    scale = np.maximum(absmax, 1e-8) / 127.0
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return {"q": jnp.asarray(q), "scale": jnp.asarray(scale, jnp.float32)}


def quantize_params(params):
    """fp param tree -> the weight_quant="int8" tree (same module paths).

    A Dense is recognized structurally: a dict whose ONLY entry is a 2-D
    "kernel" (this family's Denses are all bias-free). Anything else —
    embed (raw leaf), RMSNorm ({"scale"}), nested module dicts — recurses
    or passes through unchanged."""
    if isinstance(params, Mapping):
        keys = set(params.keys())
        if keys == {"kernel"} and getattr(params["kernel"], "ndim", 0) == 2:
            return quantize_kernel(params["kernel"])
        return {k: quantize_params(v) for k, v in params.items()}
    return params


def dequantize_kernel(qdict) -> jnp.ndarray:
    """The fp reconstruction q · scale — what QuantDense's matmul sees;
    round-trip error is bounded by scale/2 per element (half a quantization
    step). Exposed for tests and for exporting back to fp."""
    return qdict["q"].astype(jnp.float32) * qdict["scale"][None, :]
