"""Stream-fairness benchmark: how evenly do bytes spread across streams?

The reference's whole point is FAIR multi-stream striping: its BASIC engine
rotates the chunk round-robin cursor ACROSS messages, so even single-chunk
(small) messages take turns on every TCP connection; its TOKIO engine always
started at stream 0 and pinned small messages there (reference
nthread_per_socket_backend.rs:393,412 vs tokio_backend.rs:392-404 — SURVEY
hard-part #4). This benchmark makes that property measurable: a sender
pushes many single-chunk messages, then we read the engine's per-stream
byte counters (tpunet_stream_tx_bytes) and report the distribution plus
Jain's fairness index J = (Σx)² / (n·Σx²) — 1.0 is perfectly fair, 1/n is
one stream hogging everything.

    python -m benchmarks.fairness --nstreams 4 --messages 2000 --size 8192
"""

from __future__ import annotations

import argparse
import os
import sys


def _worker(rank, world, port, q, args):
    try:
        os.environ["TPUNET_NSTREAMS"] = str(args.nstreams)
        # Every message must be single-chunk: fairness then rests entirely
        # on the rotating cursor, the property under test.
        os.environ["TPUNET_MIN_CHUNKSIZE"] = str(max(args.size, 1 << 20))
        import numpy as np

        from tpunet.collectives import Communicator
        from tpunet.telemetry import metrics
        from tpunet.transport import Net

        boot = Communicator(f"127.0.0.1:{port}", rank, world)
        net = Net()
        listen = net.listen()
        handles = boot.all_gather(np.frombuffer(listen.handle, np.uint8))
        # Ring topology (world=2 degenerates to the classic pair): every
        # rank SENDS to (rank+1)%W and receives from (rank-1)%W, so at
        # W>2 all ranks stripe concurrently — fairness under contention,
        # not just on a quiet box.
        peer = bytes(handles[(rank + 1) % world].tobytes())
        send = net.connect(peer)
        boot.barrier()
        recv = listen.accept()

        buf = np.ones(args.size, np.uint8)
        out = np.empty(args.size, np.uint8)
        pending = []
        for _ in range(args.messages):
            pending.append(send.isend(buf))
            if len(pending) >= 8:
                pending.pop(0).wait()
            # Interleave one recv per send so no ring neighbor stalls on a
            # full socket buffer.
            recv.irecv(out).wait()
        for r in pending:
            r.wait()
        boot.barrier()

        per_stream = {}
        for labels, value in metrics().get("tpunet_stream_tx_bytes", {}).items():
            stream = next(
                (l.split("=")[1].strip('"') for l in labels if l.startswith("stream=")),
                None,
            )
            if stream is not None:
                per_stream[int(stream)] = int(value)
        if not per_stream:
            raise RuntimeError("no tpunet_stream_tx_bytes samples in telemetry")
        send.close(); recv.close(); listen.close(); net.close(); boot.close()
        q.put((rank, ("OK", per_stream)))
    except Exception as e:  # noqa: BLE001
        q.put((rank, (f"FAIL: {type(e).__name__}: {e}", {})))


def jain(xs) -> float:
    xs = [float(x) for x in xs]
    if not xs or sum(xs) == 0:
        return 0.0
    return sum(xs) ** 2 / (len(xs) * sum(x * x for x in xs))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nstreams", type=int, default=4)
    ap.add_argument("--messages", type=int, default=2000)
    ap.add_argument("--size", type=int, default=8192, help="bytes per message")
    ap.add_argument("-n", "--world", type=int, default=2,
                    help="ring size; >2 = all ranks stripe concurrently")
    args = ap.parse_args(argv)

    from benchmarks import check_rank_results, spawn_ranks

    results = check_rank_results(
        spawn_ranks(_worker, args.world, extra_args=(args,), timeout=1800)
    )
    print(f"# tpunet stream fairness  world={args.world} "
          f"nstreams={args.nstreams} messages={args.messages} "
          f"size={args.size}B (single-chunk)")
    worst = 1.0
    for rank in sorted(results):
        counts = [results[rank].get(i, 0) for i in range(args.nstreams)]
        j = jain(counts)
        worst = min(worst, j)
        total = sum(counts)
        pcts = " ".join(f"{100.0 * c / total if total else 0.0:5.1f}%"
                        for c in counts)
        print(f"  rank {rank} tx: {pcts}  Jain {j:.4f}")
    print(f"  worst-rank Jain fairness index: {worst:.4f}  (1.0 = perfectly "
          f"fair, {1.0 / args.nstreams:.2f} = one stream hogs)")
    return worst


if __name__ == "__main__":
    main(sys.argv[1:])
