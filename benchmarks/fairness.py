"""Stream-fairness benchmark: how evenly do bytes spread across streams?

The reference's whole point is FAIR multi-stream striping: its BASIC engine
rotates the chunk round-robin cursor ACROSS messages, so even single-chunk
(small) messages take turns on every TCP connection; its TOKIO engine always
started at stream 0 and pinned small messages there (reference
nthread_per_socket_backend.rs:393,412 vs tokio_backend.rs:392-404 — SURVEY
hard-part #4). This benchmark makes that property measurable: a sender
pushes many single-chunk messages, then we read the engine's per-stream
byte counters (tpunet_stream_tx_bytes) and report the distribution plus
Jain's fairness index J = (Σx)² / (n·Σx²) — 1.0 is perfectly fair, 1/n is
one stream hogging everything.

    python -m benchmarks.fairness --nstreams 4 --messages 2000 --size 8192

Lane mode (docs/DESIGN.md "Lanes & adaptive striping"): under ``--lanes``
the bench drives a two-Net loopback pair through the WEIGHTED stripe
scheduler, optionally delay-faulting the last lane into an asymmetric
path, and reports — all from counters — per-lane byte shares
(tpunet_lane_bytes_total), per-class Jain indices
(tpunet_stream_fairness_jain), measured lane rates (tpunet_lane_rate_bps),
restripe epochs, and the weight-convergence HALF-LIFE: the time for the
demoted lane's tpunet_lane_weight gauge to cover half the distance from
its initial to its final value.

    python -m benchmarks.fairness --lanes w=1,w=1 --delay-ms 3 --json out.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time


def _worker(rank, world, port, q, args):
    try:
        os.environ["TPUNET_NSTREAMS"] = str(args.nstreams)
        # Every message must be single-chunk: fairness then rests entirely
        # on the rotating cursor, the property under test.
        os.environ["TPUNET_MIN_CHUNKSIZE"] = str(max(args.size, 1 << 20))
        import numpy as np

        from tpunet.collectives import Communicator
        from tpunet.telemetry import metrics
        from tpunet.transport import Net

        boot = Communicator(f"127.0.0.1:{port}", rank, world)
        net = Net()
        listen = net.listen()
        handles = boot.all_gather(np.frombuffer(listen.handle, np.uint8))
        # Ring topology (world=2 degenerates to the classic pair): every
        # rank SENDS to (rank+1)%W and receives from (rank-1)%W, so at
        # W>2 all ranks stripe concurrently — fairness under contention,
        # not just on a quiet box.
        peer = bytes(handles[(rank + 1) % world].tobytes())
        send = net.connect(peer)
        boot.barrier()
        recv = listen.accept()

        buf = np.ones(args.size, np.uint8)
        out = np.empty(args.size, np.uint8)
        pending = []
        for _ in range(args.messages):
            pending.append(send.isend(buf))
            if len(pending) >= 8:
                pending.pop(0).wait()
            # Interleave one recv per send so no ring neighbor stalls on a
            # full socket buffer.
            recv.irecv(out).wait()
        for r in pending:
            r.wait()
        boot.barrier()

        per_stream = {}
        for labels, value in metrics().get("tpunet_stream_tx_bytes", {}).items():
            stream = next(
                (l.split("=")[1].strip('"') for l in labels if l.startswith("stream=")),
                None,
            )
            if stream is not None:
                per_stream[int(stream)] = int(value)
        if not per_stream:
            raise RuntimeError("no tpunet_stream_tx_bytes samples in telemetry")
        send.close(); recv.close(); listen.close(); net.close(); boot.close()
        q.put((rank, ("OK", per_stream)))
    except Exception as e:  # noqa: BLE001
        q.put((rank, (f"FAIL: {type(e).__name__}: {e}", {})))


def jain(xs) -> float:
    xs = [float(x) for x in xs]
    if not xs or sum(xs) == 0:
        return 0.0
    return sum(xs) ** 2 / (len(xs) * sum(x * x for x in xs))


# ---------------------------------------------------------------------------
# Lane mode: weighted striping over an (optionally) asymmetric loopback pair.


def _lane_gauge(metrics, family, labels_fn):
    out = {}
    for key, value in metrics.get(family, {}).items():
        lab = labels_fn(key)
        if "lane" in lab and lab.get("dir") in (None, "tx"):
            out[int(lab["lane"])] = int(value)
    return out


def run_lanes(args) -> dict:
    os.environ["TPUNET_LANES"] = args.lanes
    os.environ["TPUNET_LANE_ADAPT"] = "0" if args.no_adapt else "1"
    os.environ["TPUNET_LANE_ADAPT_MS"] = str(args.adapt_ms)
    os.environ["TPUNET_MIN_CHUNKSIZE"] = str(max(1, args.size // 8))
    os.environ["TPUNET_CRC"] = "1"
    import numpy as np

    from tpunet import telemetry
    from tpunet import transport as tp
    from tpunet.transport import Net

    nlanes = len(args.lanes.split(","))
    telemetry.reset()
    ns, nr = Net(), Net()
    lc = nr.listen()
    got = {}
    th = threading.Thread(target=lambda: got.setdefault("rc", lc.accept()))
    th.start()
    sc = ns.connect(lc.handle)
    th.join()
    rc = got["rc"]
    weight_trace = []  # (seconds, {lane: weight}) — the convergence record
    try:
        if args.delay_ms:
            tp.fault_inject(
                f"stream={nlanes - 1}:side=send:action=delay={args.delay_ms}")
        src = np.arange(args.size, dtype=np.uint8)
        t0 = time.perf_counter()
        batch = 10
        for start in range(0, args.messages, batch):
            for _ in range(min(batch, args.messages - start)):
                dst = np.zeros_like(src)
                r = rc.irecv(dst)
                sc.isend(src).wait(timeout=60)
                r.wait(timeout=60)
                if not np.array_equal(src, dst):
                    raise RuntimeError("payload corrupt — lane layout desync?")
            weight_trace.append((
                time.perf_counter() - t0,
                _lane_gauge(telemetry.metrics(), "tpunet_lane_weight",
                            telemetry.labels),
            ))
        elapsed = time.perf_counter() - t0
    finally:
        tp.fault_clear()
        for c in (sc, rc, lc):
            c.close()
        ns.close()
        nr.close()

    m = telemetry.metrics()
    lanes = _lane_gauge(m, "tpunet_lane_bytes_total", telemetry.labels)
    rates = _lane_gauge(m, "tpunet_lane_rate_bps", telemetry.labels)
    total = sum(lanes.values())
    shares = {str(k): round(v / total, 4) for k, v in sorted(lanes.items())} if total else {}
    jain_by_class = {}
    for key, value in m.get("tpunet_stream_fairness_jain", {}).items():
        lab = telemetry.labels(key)
        if lab.get("dir") == "tx":
            jain_by_class[lab.get("class", "?")] = round(float(value), 4)

    # Weight-convergence half-life: for the lane whose weight moved the
    # most, the first trace time at which it had covered half the distance
    # from its initial to its final value. None when weights never moved
    # (uniform control / symmetric paths).
    half_life_s = None
    if weight_trace:
        final = weight_trace[-1][1]
        initial = weight_trace[0][1]
        mover, dist = None, 0
        for lane in final:
            d = abs(final.get(lane, 1) - initial.get(lane, 1))
            if d > dist:
                mover, dist = lane, d
        if mover is not None and dist > 0:
            target = initial.get(mover, 1) + (final[mover] - initial.get(mover, 1)) / 2
            for t, ws in weight_trace:
                w = ws.get(mover)
                if w is None:
                    continue
                if (final[mover] >= initial.get(mover, 1) and w >= target) or \
                   (final[mover] < initial.get(mover, 1) and w <= target):
                    half_life_s = round(t, 4)
                    break

    return {
        "mode": "lanes",
        "lanes": args.lanes,
        "adapt": not args.no_adapt,
        "delay_ms": args.delay_ms,
        "messages": args.messages,
        "size": args.size,
        "elapsed_s": round(elapsed, 3),
        "lane_tx_bytes": {str(k): v for k, v in sorted(lanes.items())},
        "lane_tx_shares": shares,
        "lane_rate_bps": {str(k): v for k, v in sorted(rates.items())},
        "lane_weights": {str(k): v for k, v in
                         sorted(weight_trace[-1][1].items())} if weight_trace else {},
        "jain_tx_by_class": jain_by_class,
        "restripe_events": int(sum(
            m.get("tpunet_restripe_events_total", {}).values())),
        "weight_half_life_s": half_life_s,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--nstreams", type=int, default=4)
    ap.add_argument("--messages", type=int, default=2000)
    ap.add_argument("--size", type=int, default=8192, help="bytes per message")
    ap.add_argument("-n", "--world", type=int, default=2,
                    help="ring size; >2 = all ranks stripe concurrently")
    ap.add_argument("--lanes", default=None, metavar="SPEC",
                    help="lane mode: TPUNET_LANES spec (e.g. w=1,w=1) — "
                         "weighted striping over a loopback pair; reports "
                         "per-lane shares / rates / weights / half-life")
    ap.add_argument("--delay-ms", type=int, default=0,
                    help="lane mode: delay-fault the LAST lane by this many "
                         "ms per chunk (the asymmetric-path injection)")
    ap.add_argument("--adapt-ms", type=int, default=20,
                    help="lane mode: adaptation tick (TPUNET_LANE_ADAPT_MS)")
    ap.add_argument("--no-adapt", action="store_true",
                    help="lane mode: pin base weights (uniform control)")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="also write the result object to PATH (stdout keeps "
                         "the one-JSON-line contract in lane mode)")
    args = ap.parse_args(argv)

    if args.lanes:
        if args.messages == 2000 and args.size == 8192:
            args.messages, args.size = 400, 256 << 10  # lane-mode defaults
        out = run_lanes(args)
        print(json.dumps(out))
        if args.json:
            with open(args.json, "w") as f:
                json.dump(out, f, indent=2)
        return out

    from benchmarks import check_rank_results, spawn_ranks

    results = check_rank_results(
        spawn_ranks(_worker, args.world, extra_args=(args,), timeout=1800)
    )
    print(f"# tpunet stream fairness  world={args.world} "
          f"nstreams={args.nstreams} messages={args.messages} "
          f"size={args.size}B (single-chunk)")
    worst = 1.0
    per_rank = {}
    for rank in sorted(results):
        counts = [results[rank].get(i, 0) for i in range(args.nstreams)]
        j = jain(counts)
        worst = min(worst, j)
        total = sum(counts)
        pcts = " ".join(f"{100.0 * c / total if total else 0.0:5.1f}%"
                        for c in counts)
        per_rank[str(rank)] = {"tx_bytes": counts, "jain": round(j, 4)}
        print(f"  rank {rank} tx: {pcts}  Jain {j:.4f}")
    print(f"  worst-rank Jain fairness index: {worst:.4f}  (1.0 = perfectly "
          f"fair, {1.0 / args.nstreams:.2f} = one stream hogs)")
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"mode": "streams", "world": args.world,
                       "nstreams": args.nstreams, "messages": args.messages,
                       "size": args.size, "per_rank": per_rank,
                       "worst_jain": round(worst, 4)}, f, indent=2)
    return worst


if __name__ == "__main__":
    main(sys.argv[1:])
