"""all_reduce_perf-style bandwidth sweep over the tpunet transport.

The in-repo replacement for the external harness the reference relied on
(nccl-tests `all_reduce_perf -b 8 -e 128M -f 2 -g 1` under mpirun,
reference README.md:20-44). Sweeps message sizes 8 B -> 128 MiB (x2 steps by
default) and prints the familiar table: size, count, time, algbw, busbw.

Modes:
  --op p2p            raw isend/irecv one-way stream between 2 ranks
  --op allreduce      ring AllReduce        (busbw = algbw * 2(W-1)/W)
  --op allgather      ring AllGather        (busbw = algbw * (W-1)/W)
  --op reducescatter  ring ReduceScatter    (busbw = algbw * (W-1)/W)
  --op alltoall       AllToAll (TPUNET_A2A=pairwise|ring picks the impl;
                      busbw = algbw * (W-1)/W, alltoall_perf convention)

Launching:
  Local loopback (spawns -n worker processes itself):
      python -m benchmarks.busbw_sweep --op allreduce -n 2 --nstreams 4
  Multi-host (one process per host, like mpirun): set TPUNET_RANK,
  TPUNET_WORLD_SIZE, TPUNET_COORDINATOR and pass --external.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from benchmarks import spawn_ranks


def parse_size(s: str) -> int:
    s = s.strip().upper()
    mult = 1
    for suffix, m in (("G", 1 << 30), ("M", 1 << 20), ("K", 1 << 10)):
        if s.endswith(suffix):
            mult = m
            s = s[: -len(suffix)]
            break
    return int(float(s) * mult)


def sweep_sizes(begin: int, end: int, factor: int) -> list[int]:
    sizes = []
    n = max(begin, 1)
    while n <= end:
        sizes.append(n)
        n *= factor
    return sizes


def _busbw_factor(op: str, world: int) -> float:
    if op in ("allreduce", "psum"):  # psum = the jit(dcn_psum) sweep
        return 2.0 * (world - 1) / world
    if op in ("allgather", "reducescatter", "alltoall"):
        # alltoall: each rank ships (W-1)/W of its S bytes off-node
        # (nccl-tests alltoall_perf convention).
        return float(world - 1) / world
    return 1.0  # p2p


def _run_collective_rank(rank, world, coordinator, args, emit):
    import numpy as np

    from tpunet import telemetry
    from tpunet.collectives import Communicator

    comm = Communicator(coordinator=coordinator, rank=rank, world_size=world)
    rows = []
    for nbytes in sweep_sizes(args.begin, args.end, args.factor):
        # nccl-tests convention: `size` is the TOTAL vector size S. For
        # AllGather/ReduceScatter each rank's shard is S/W; algbw = S/t and
        # busbw = algbw * (W-1)/W for both, 2(W-1)/W for AllReduce.
        count = max(nbytes // 4, 1)
        if args.op == "allgather":
            shard = np.full(max(count // world, 1), float(rank + 1), np.float32)
            count = shard.size * world
            run = lambda: comm.all_gather(shard)
        elif args.op == "alltoall":
            # Per-(source, block) values so the provenance assert catches
            # block-slot permutation bugs, not just wrong-source ones.
            blocks = np.stack([
                np.full(max(count // world, 1), float(rank * world + j),
                        np.float32)
                for j in range(world)
            ])
            count = blocks.size
            run = lambda: comm.all_to_all(blocks)
        elif args.op == "reducescatter":
            big = np.full(max(count // world, 1) * world, float(rank + 1), np.float32)
            count = big.size
            run = lambda: comm.reduce_scatter(big)
        else:
            arr = np.full(count, float(rank + 1), np.float32)
            run = lambda: comm.all_reduce(arr)
        iters = args.iters if nbytes >= (1 << 16) else args.iters * 4
        for _ in range(args.warmup):
            run()
        comm.barrier()
        telemetry.reset()  # codec counters cover exactly the timed window
        t0 = time.perf_counter()
        for _ in range(iters):
            out = run()
        comm.barrier()
        dt = (time.perf_counter() - t0) / iters
        # Wire-compression ratio over the timed window, straight from the
        # native counters (tpunet_codec_wire_ratio = encoded/payload bytes;
        # 1.0 on the f32 lane) — the noise-immune number BENCH json records
        # next to GB/s.
        m = telemetry.metrics()
        wire_ratio = next(
            iter(m.get("tpunet_codec_wire_ratio", {}).values()), 1.0)
        if args.op == "allreduce":
            expect = sum(r + 1 for r in range(world))
            assert out[0] == expect, f"bad allreduce result {out[0]} != {expect}"
        elif args.op == "alltoall":
            for j in range(world):  # block j = source j's block FOR this rank
                expect = float(j * world + rank)
                assert out[j][0] == expect, \
                    f"bad alltoall block {j} at rank {rank}: {out[j][0]} != {expect}"
        rows.append((count * 4, count, dt, wire_ratio))
    comm.close()
    if rank == 0:
        emit(rows, world)


def _run_dispatch_rank(rank, world, coordinator, args, emit):
    """--emit-dispatch lane: time the allreduce sweep under EACH schedule
    (ring / rhd / tree — plus hier when the topology is hierarchical: a
    real multi-host launch, or --fake-hosts H splitting the local spawn
    into H fake hosts via TPUNET_HOST_ID — one communicator per algo on
    coordinator port +0/+1/...), take the MEDIAN of 3 timed reps per
    (algo, size) — a single-shot winner is noise-picked on a busy host —
    and write the winner table as the TPUNET_DISPATCH_TABLE JSON
    (docs/DESIGN.md "Schedules & algorithm selection"). Adjacent sizes with
    the same winner coalesce into one entry; the last run is open-ended
    (max_bytes 0). A table routing sizes to "hier" is then loadable on the
    matching topology — the emitted table can select it per size."""
    import statistics

    import numpy as np

    from tpunet.collectives import Communicator

    host, port = coordinator.rsplit(":", 1)
    algos = ["ring", "rhd", "tree"]
    # hier only sweeps on a hierarchical topology (>= 2 hosts); on a flat
    # one it would silently time the ring twice and could noise-win rows.
    if getattr(args, "fake_hosts", 0) or os.environ.get("TPUNET_HOST_ID"):
        algos.append("hier")
    sizes = sweep_sizes(args.begin, args.end, args.factor)
    reps = 3
    medians: dict[str, dict[int, float]] = {a: {} for a in algos}
    for ai, algo in enumerate(algos):
        comm = Communicator(coordinator=f"{host}:{int(port) + ai}", rank=rank,
                            world_size=world, algo=algo)
        for nbytes in sizes:
            count = max(nbytes // 4, 1)
            arr = np.full(count, float(rank + 1), np.float32)
            iters = args.iters if nbytes >= (1 << 16) else args.iters * 4
            for _ in range(args.warmup):
                comm.all_reduce(arr)
            samples = []
            for _ in range(reps):
                comm.barrier()
                t0 = time.perf_counter()
                for _ in range(iters):
                    out = comm.all_reduce(arr)
                comm.barrier()
                samples.append((time.perf_counter() - t0) / iters)
            assert out[0] == sum(r + 1 for r in range(world)), "bad allreduce result"
            medians[algo][nbytes] = statistics.median(samples)
        comm.close()
    if rank != 0:
        return
    winners = {n: min(algos, key=lambda a: medians[a][n]) for n in sizes}
    entries = []
    for i, n in enumerate(sizes):
        if entries and entries[-1]["algo"] == winners[n]:
            entries[-1]["max_bytes"] = n
        else:
            entries.append({"coll": "allreduce", "world": world,
                            "max_bytes": n, "algo": winners[n]})
    if entries:
        entries[-1]["max_bytes"] = 0  # last run is open-ended
    table = {"version": 1, "op": "allreduce", "world": world,
             "reps": reps, "entries": entries}
    with open(args.emit_dispatch, "w") as f:
        json.dump(table, f, indent=1)
    print(f"# tpunet dispatch sweep  world={world} reps={reps} "
          f"-> {args.emit_dispatch}")
    print(f"# {'size':>12} " + " ".join(f"{a + '(us)':>12}" for a in algos)
          + f" {'winner':>8}")
    for n in sizes:
        print(f"  {n:>12} "
              + " ".join(f"{medians[a][n] * 1e6:>12.1f}" for a in algos)
              + f" {winners[n]:>8}")
    for e in entries:
        bound = "inf" if e["max_bytes"] == 0 else str(e["max_bytes"])
        print(f"#   allreduce <= {bound} B -> {e['algo']}")


def _run_p2p_rank(rank, world, coordinator, args, emit):
    """One-way stream: rank 0 sends, rank 1 receives; handles swap over the
    collectives bootstrap (the role NCCL's OOB bootstrap played)."""
    import numpy as np

    from tpunet.collectives import Communicator
    from tpunet.transport import Net

    assert world == 2, "p2p sweep needs exactly 2 ranks"
    boot = Communicator(coordinator=coordinator, rank=rank, world_size=world)
    net = Net()
    listen = net.listen()
    handles = boot.all_gather(np.frombuffer(listen.handle, np.uint8))
    peer = bytes(handles[1 - rank].tobytes())
    if rank == 0:
        send = net.connect(peer)
        boot.barrier()
        recv = listen.accept()
    else:
        boot.barrier()
        recv = listen.accept()
        send = net.connect(peer)

    rows = []
    depth = 4  # keep a few requests in flight, like NCCL's proxy (<=8)
    for nbytes in sweep_sizes(args.begin, args.end, args.factor):
        buf = np.ones(nbytes, np.uint8)
        out = np.empty(nbytes, np.uint8)
        iters = args.iters if nbytes >= (1 << 16) else args.iters * 4
        boot.barrier()
        t0 = time.perf_counter()
        pending = []
        for _ in range(iters):
            if rank == 0:
                pending.append(send.isend(buf))
            else:
                pending.append(recv.irecv(out))
            if len(pending) >= depth:
                pending.pop(0).wait()
        for r in pending:
            r.wait()
        boot.barrier()
        dt = (time.perf_counter() - t0) / iters
        rows.append((nbytes, nbytes, dt))
    send.close()
    recv.close()
    listen.close()
    net.close()
    boot.close()
    if rank == 0:
        emit(rows, world)


def make_table_emitter(op: str, nstreams=None, engine=None, json_path: str = "",
                       wire_dtype=None):
    """Shared all_reduce_perf-style table emitter (also used by psum_sweep,
    keeping the two sweeps' output directly comparable). nstreams/engine/
    wire_dtype default to the env the workers ran with. Rows may carry a
    4th element — wire_bytes_per_payload_byte from the codec counters —
    which is printed and recorded when present (psum_sweep's 3-tuples keep
    working)."""
    if nstreams is None:
        nstreams = os.environ.get("TPUNET_NSTREAMS", "2")
    if engine is None:
        engine = os.environ.get("TPUNET_IMPLEMENT", "BASIC")
    if wire_dtype is None:
        wire_dtype = os.environ.get("TPUNET_WIRE_DTYPE", "f32")

    def emit(rows, world):
        factor = _busbw_factor(op, world)
        print(f"# tpunet {op} sweep  world={world} "
              f"nstreams={nstreams} engine={engine} wire_dtype={wire_dtype}")
        print(f"# {'size':>12} {'count':>12} {'time(us)':>12} "
              f"{'algbw(GB/s)':>12} {'busbw(GB/s)':>12} {'wireB/B':>8}")
        out = []
        for row in rows:
            nbytes, count, dt = row[:3]
            wire_ratio = row[3] if len(row) > 3 else None
            algbw = nbytes / dt / 1e9
            busbw = algbw * factor
            print(f"  {nbytes:>12} {count:>12} {dt * 1e6:>12.1f} "
                  f"{algbw:>12.3f} {busbw:>12.3f} "
                  f"{'' if wire_ratio is None else format(wire_ratio, '8.3f')}")
            entry = {"bytes": nbytes, "time_us": dt * 1e6,
                     "algbw_gbps": algbw, "busbw_gbps": busbw}
            if wire_ratio is not None:
                entry["wire_bytes_per_payload_byte"] = wire_ratio
            out.append(entry)
        if json_path:
            with open(json_path, "w") as f:
                json.dump({"op": op, "world": world,
                           "wire_dtype": wire_dtype, "rows": out}, f)
    return emit


def _emit_table(args):
    return make_table_emitter(args.op, json_path=args.json,
                              wire_dtype=getattr(args, "wire_dtype", "") or None)


def _worker(rank, world, port, q, args):
    try:
        if args.nstreams:
            os.environ["TPUNET_NSTREAMS"] = str(args.nstreams)
        if args.wire_dtype:
            os.environ["TPUNET_WIRE_DTYPE"] = args.wire_dtype
        if getattr(args, "fake_hosts", 0):
            # Contiguous equal groups: ranks [0, W/H) on fake host 0, etc.
            # (uniform ranks/host is what makes `hier` usable). TPUNET_SHM=1
            # gives the intra-"host" pairs ring segments, so the sweep's
            # hier lane exercises the real SHM-intra + TCP-inter split.
            os.environ["TPUNET_HOST_ID"] = (
                f"sweephost{rank * args.fake_hosts // world}")
            os.environ.setdefault("TPUNET_SHM", "1")
        if args.emit_dispatch:
            run = _run_dispatch_rank
        else:
            run = _run_p2p_rank if args.op == "p2p" else _run_collective_rank
        run(rank, world, f"127.0.0.1:{port}", args, _emit_table(args))
        q.put((rank, "OK"))
    except Exception as e:  # noqa: BLE001
        q.put((rank, f"FAIL: {type(e).__name__}: {e}"))


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--op", default="allreduce",
                    choices=["p2p", "allreduce", "allgather", "reducescatter",
                             "alltoall"])
    ap.add_argument("-b", "--begin", type=parse_size, default=8)
    ap.add_argument("-e", "--end", type=parse_size, default=128 << 20)
    ap.add_argument("-f", "--factor", type=int, default=2)
    ap.add_argument("-n", "--world", type=int, default=2)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--warmup", type=int, default=1)
    ap.add_argument("--nstreams", type=int, default=0, help="override TPUNET_NSTREAMS")
    ap.add_argument("--wire-dtype", dest="wire_dtype", default="",
                    choices=["", "f32", "bf16", "int8"],
                    help="collective wire codec lane (sets TPUNET_WIRE_DTYPE "
                         "in the workers; BENCH json records the measured "
                         "wire_bytes_per_payload_byte from the codec counters)")
    ap.add_argument("--json", default="", help="also dump rows to this file")
    ap.add_argument("--emit-dispatch", dest="emit_dispatch", default="",
                    help="time the allreduce sweep under each schedule "
                         "(ring/rhd/tree, + hier on a hierarchical "
                         "topology; median of 3 reps per size) and write "
                         "the winner table to this path as "
                         "TPUNET_DISPATCH_TABLE JSON (uses coordinator "
                         "ports +0/+1/...)")
    ap.add_argument("--fake-hosts", dest="fake_hosts", type=int, default=0,
                    help="split the local spawn into this many fake "
                         "'hosts' (contiguous equal rank groups via "
                         "TPUNET_HOST_ID, TPUNET_SHM=1 within them) so the "
                         "hier schedule engages on one box — the "
                         "--emit-dispatch sweep then times it per size")
    ap.add_argument("--external", action="store_true",
                    help="run as one rank; rank/world/coordinator from env")
    args = ap.parse_args()

    from tpunet import _native

    _native.build_native()

    if args.external:
        if args.wire_dtype:
            os.environ["TPUNET_WIRE_DTYPE"] = args.wire_dtype
        rank = int(os.environ.get("TPUNET_RANK", os.environ.get("RANK", "0")))
        world = int(os.environ.get("TPUNET_WORLD_SIZE", os.environ.get("WORLD_SIZE", "1")))
        coord = os.environ.get("TPUNET_COORDINATOR", "127.0.0.1:29500")
        if args.emit_dispatch:
            run = _run_dispatch_rank
        else:
            run = _run_p2p_rank if args.op == "p2p" else _run_collective_rank
        run(rank, world, coord, args, _emit_table(args))
        return

    results = spawn_ranks(_worker, args.world, extra_args=(args,), timeout=3600)
    fails = [(r, s) for r, s in sorted(results.items()) if s != "OK"]
    if fails:
        print(f"FAILED ranks: {fails}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
