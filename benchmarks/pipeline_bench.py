"""Pipeline-parallel stage bench: microbatch chains over per-stage links.

Drives tpunet.workloads.pipeline across W spawned stages (optionally split
into TPUNET_HOST_ID fake hosts so inter-stage hops cross a "DCN" boundary):
stage 0 feeds N microbatches of --mb-bytes, every stage applies a marker
transform and forwards with ticket `after=` ordering, the last stage
verifies each microbatch passed through every stage exactly once.

Reported (counters + wall-clock; correctness is the gate, wall-clock the
context): per-microbatch pipe latency p50/p99 at the last stage, aggregate
bytes in/out per stage (tpunet_isend/irecv counters), microbatches/s.

Run:
  python -m benchmarks.pipeline_bench --world 4 --n-micro 32 --json
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _stage_main(rank, world, port, q, args):
    try:
        os.environ.update({"TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1"})
        if args.fake_hosts > 1:
            os.environ["TPUNET_SHM"] = "1"
            os.environ["TPUNET_HOST_ID"] = \
                f"pipehost{rank // (world // args.fake_hosts)}"
        import numpy as np

        from tpunet import telemetry
        from tpunet.collectives import Communicator
        from tpunet.workloads.pipeline import PipelineStage

        n = args.mb_bytes // 4
        comm = Communicator(f"127.0.0.1:{port}", rank, world)
        with PipelineStage(comm, traffic_class=args.traffic_class or None) as st:
            telemetry.reset()

            def fn(x):
                return x + 1.0  # each stage stamps one increment

            t0 = time.monotonic()
            if st.is_first:
                mbs = [np.full(n, float(i), np.float32)
                       for i in range(args.n_micro)]
                out = st.run(fn, microbatches=mbs)
            else:
                out = st.run(fn, n_micro=args.n_micro, mb_shape=(n,))
            dt = time.monotonic() - t0
            stats = {"ok": True, "seconds": dt,
                     "mb_per_s": args.n_micro / dt if dt > 0 else None}
            if st.is_last:
                for i, y in enumerate(out):
                    assert np.all(y == i + world), \
                        f"microbatch {i} corrupted: {y[0]} != {i + world}"
                stats["verified"] = len(out)
            m = telemetry.metrics()
            stats["isend_bytes"] = int(sum(
                m.get("tpunet_isend_nbytes_sum", {}).values()))
            stats["irecv_bytes"] = int(sum(
                m.get("tpunet_irecv_nbytes_sum", {}).values()))
            q.put((rank, stats))
        comm.close()
    except Exception as e:  # noqa: BLE001
        import traceback

        q.put((rank, {"ok": False, "error": f"{type(e).__name__}: {e}",
                      "trace": traceback.format_exc()}))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--world", type=int, default=4)
    ap.add_argument("--n-micro", type=int, default=32)
    ap.add_argument("--mb-bytes", type=int, default=1 << 20)
    ap.add_argument("--fake-hosts", type=int, default=1)
    ap.add_argument("--traffic-class", default="",
                    choices=["", "latency", "bulk"])
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    if args.fake_hosts > 1 and args.world % args.fake_hosts:
        ap.error("--world must divide evenly into --fake-hosts")

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests"))
    from conftest import free_port

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    procs = [ctx.Process(target=_stage_main, args=(r, args.world, port, q, args))
             for r in range(args.world)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(args.world):
            rank, res = q.get(timeout=600)
            results[rank] = res
    finally:
        for p in procs:
            p.join(timeout=60)
            if p.is_alive():
                p.kill()
    failed = {r: v for r, v in results.items() if not v.get("ok")}
    if failed:
        print(json.dumps(failed, indent=2))
        return 1
    assert results[args.world - 1].get("verified") == args.n_micro, results
    if args.json:
        print(json.dumps({"world": args.world, "n_micro": args.n_micro,
                          "mb_bytes": args.mb_bytes, "per_stage": results},
                         indent=2, sort_keys=True))
    else:
        for r in sorted(results):
            v = results[r]
            print(f"stage {r}: {v['seconds']:.3f}s, {v['mb_per_s']:.1f} mb/s, "
                  f"tx {v['isend_bytes']}B rx {v['irecv_bytes']}B"
                  + (f", verified {v['verified']}" if "verified" in v else ""))
    return 0


if __name__ == "__main__":
    sys.exit(main())
