"""Per-kernel on-TPU compile+run smoke, run by bench.py before the model tier.

Round-2 lesson: the model tier hardcoded flash attention, so one Mosaic
rejection wiped out the whole hardware story (BENCH_r02 fell back to CPU
with no per-kernel signal). This module compiles and runs each Pallas
kernel on a tiny input and reports per-kernel status, so bench.py can
(a) emit a "kernels" line item independent of the model tier, and
(b) drop only the broken kernel to its fallback instead of leaving the chip.

Prints ONE JSON line: {"flash_fwd": "ok"|"<error>", "flash_bwd": ...,
"platform": str}. Exit code 0 as long as the probe itself ran.
"""

from __future__ import annotations

import json


def _short(e: Exception) -> str:
    return f"{type(e).__name__}: {str(e).splitlines()[0][:300]}"


def _parity(a, b) -> float:
    """Max error relative to the reference's scale — an absolute threshold
    misfires when the compared quantity's magnitude varies (e.g. GQA
    gradients sum a whole group of heads)."""
    import jax.numpy as jnp

    a32, b32 = a.astype(jnp.float32), b.astype(jnp.float32)
    return float(jnp.max(jnp.abs(a32 - b32)) / jnp.maximum(jnp.max(jnp.abs(b32)), 1.0))


def run_smoke() -> dict:
    import jax
    import jax.numpy as jnp

    from tpunet.ops.flash_attention import attention_reference, flash_attention

    out: dict = {"platform": jax.default_backend()}
    # Small but tile-shaped: block-sized seq, MXU-width head_dim, bf16 like
    # the headline config (dtype changes the Mosaic tiling rules).
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 256, 4, 128), jnp.bfloat16)
    ref = attention_reference(q, q, q, True)

    try:
        o = jax.jit(lambda x: flash_attention(x, x, x, True))(q)
        err = _parity(o, ref)
        out["flash_fwd"] = "ok" if err < 0.02 else f"parity {err:.3e}"
    except Exception as e:  # noqa: BLE001 — any failure is the signal here
        out["flash_fwd"] = _short(e)

    try:
        g = jax.jit(jax.grad(lambda x: jnp.sum(flash_attention(x, x, x, True))))(q)
        gr = jax.jit(jax.grad(lambda x: jnp.sum(attention_reference(x, x, x, True))))(q)
        err = _parity(g, gr)
        out["flash_bwd"] = "ok" if err < 0.06 else f"parity {err:.3e}"
    except Exception as e:  # noqa: BLE001
        out["flash_bwd"] = _short(e)

    # GQA: the kv BlockSpec index_maps (bh // group) and the group-wide
    # dK/dV blocks are distinct Mosaic programs from the MHA case — smoke
    # them separately so a rejection is its own line item. Thresholds: bwd
    # allows 6% relative (bf16 grads accumulate ~1% ulp noise over S=256
    # sums; a wrong kernel is O(1) off), fwd 2%.
    kv = jax.random.normal(jax.random.PRNGKey(1), (1, 256, 2, 128), jnp.bfloat16)
    gref = attention_reference(
        q, jnp.repeat(kv, 2, axis=2), jnp.repeat(kv, 2, axis=2), True
    )
    try:
        o = jax.jit(lambda q, kv: flash_attention(q, kv, kv, True))(q, kv)
        err = _parity(o, gref)
        out["flash_gqa_fwd"] = "ok" if err < 0.02 else f"parity {err:.3e}"
    except Exception as e:  # noqa: BLE001
        out["flash_gqa_fwd"] = _short(e)

    try:
        g = jax.jit(jax.grad(
            lambda kv: jnp.sum(flash_attention(q, kv, kv, True))))(kv)
        gr = jax.jit(jax.grad(lambda kv: jnp.sum(attention_reference(
            q, jnp.repeat(kv, 2, axis=2), jnp.repeat(kv, 2, axis=2), True))))(kv)
        err = _parity(g, gr)
        out["flash_gqa_bwd"] = "ok" if err < 0.06 else f"parity {err:.3e}"
    except Exception as e:  # noqa: BLE001
        out["flash_gqa_bwd"] = _short(e)

    # Sliding window: the k-block loop gains a LOWER bound in fwd
    # (j_start from qi*bq - (window-1)) and an UPPER bound in the dK/dV
    # pass — new Mosaic programs reachable from the public model API
    # (attn_window=), so they get their own line items. window=192 with
    # S=256, bk=128 exercises both a fully-inside and a partially-masked
    # k-block on each side of the boundary.
    wref = attention_reference(q, q, q, True, window=192)
    try:
        o = jax.jit(lambda x: flash_attention(x, x, x, True, window=192))(q)
        err = _parity(o, wref)
        out["flash_window_fwd"] = "ok" if err < 0.02 else f"parity {err:.3e}"
    except Exception as e:  # noqa: BLE001
        out["flash_window_fwd"] = _short(e)

    try:
        g = jax.jit(jax.grad(
            lambda x: jnp.sum(flash_attention(x, x, x, True, window=192))))(q)
        gr = jax.jit(jax.grad(lambda x: jnp.sum(
            attention_reference(x, x, x, True, window=192))))(q)
        err = _parity(g, gr)
        out["flash_window_bwd"] = "ok" if err < 0.06 else f"parity {err:.3e}"
    except Exception as e:  # noqa: BLE001
        out["flash_window_bwd"] = _short(e)

    # GQA x window COMBINED: the kv-head index maps and the window's k-loop
    # bounds compose in one kernel — reachable from the public API
    # (n_kv_heads + attn_window together), and a combination Mosaic could
    # reject even when each passes alone.
    gwref = attention_reference(
        q, jnp.repeat(kv, 2, axis=2), jnp.repeat(kv, 2, axis=2), True,
        window=192)
    try:
        o = jax.jit(lambda q, kv: flash_attention(q, kv, kv, True,
                                                  window=192))(q, kv)
        err = _parity(o, gwref)
        out["flash_gqa_window_fwd"] = "ok" if err < 0.02 else f"parity {err:.3e}"
    except Exception as e:  # noqa: BLE001
        out["flash_gqa_window_fwd"] = _short(e)

    try:
        g = jax.jit(jax.grad(
            lambda kv: jnp.sum(flash_attention(q, kv, kv, True, window=192))))(kv)
        gr = jax.jit(jax.grad(lambda kv: jnp.sum(attention_reference(
            q, jnp.repeat(kv, 2, axis=2), jnp.repeat(kv, 2, axis=2), True,
            window=192))))(kv)
        err = _parity(g, gr)
        out["flash_gqa_window_bwd"] = "ok" if err < 0.06 else f"parity {err:.3e}"
    except Exception as e:  # noqa: BLE001
        out["flash_gqa_window_bwd"] = _short(e)

    return out


def main() -> None:
    print(json.dumps(run_smoke()))


if __name__ == "__main__":
    main()
