"""Capture a jax.profiler trace of the headline train step (TPU or CPU).

The chained-timing tools (benchmarks.mfu_attribution) attribute step time
by re-timing isolated segments; a profiler trace is the ground-truth
cross-check — per-op device timelines straight from the runtime. This
wraps the headline step in `jax.profiler.trace` for a few post-warmup
steps and reports where the trace landed (point perfetto/tensorboard at
it). Kept separate from chip_session's measurement steps because the
profiler plugin may not function over the tunneled platform — a failed
capture must never cost measurement time.

Usage: python -m benchmarks.profile_capture [--out DIR] [--steps 3]
       [--platform tpu|cpu] [--d ... --layers ... etc like mfu_attribution]
Prints ONE JSON line: {"trace_dir": ..., "files": N, "step_ms": ...}.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import time


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="/tmp/tpunet_trace")
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--platform", choices=["tpu", "cpu"], default="cpu")
    ap.add_argument("--d", type=int, default=2048)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--ff", type=int, default=8192)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    args = ap.parse_args(argv)
    if args.steps < 1:
        raise SystemExit(f"--steps must be >= 1, got {args.steps}")

    if args.platform == "cpu":
        from benchmarks import reassert_jax_platform

        reassert_jax_platform("cpu")
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tpunet.models import Transformer
    from tpunet.train import create_train_state, make_train_step

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if args.platform == "tpu" and not on_tpu:
        raise SystemExit(f"requested tpu, got {dev.platform}")
    if not on_tpu:  # CPU smoke shape — the tool contract, not the numbers
        args.d, args.layers, args.ff, args.heads = 64, 2, 128, 4
        args.vocab, args.batch, args.seq = 512, 2, 128

    model = Transformer(
        vocab=args.vocab, d_model=args.d, n_layers=args.layers,
        n_heads=args.heads, d_ff=args.ff,
        compute_dtype=jnp.bfloat16 if on_tpu else jnp.float32,
        attn_impl="flash" if on_tpu else "reference", remat=on_tpu)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, args.vocab, (args.batch, args.seq)),
                         jnp.int32)
    labels = jnp.roll(tokens, -1, axis=1)
    tx = optax.adamw(3e-4)
    state, _ = create_train_state(model, jax.random.PRNGKey(0), tokens, tx)
    step = make_train_step(model, tx)

    # Warmup/compile OUTSIDE the trace (a trace dominated by compilation is
    # useless for per-op attribution).
    for _ in range(2):
        state, loss = step(state, tokens, labels, jax.random.PRNGKey(1))
    float(loss)  # sync

    os.makedirs(args.out, exist_ok=True)
    t0 = time.perf_counter()
    with jax.profiler.trace(args.out):
        for _ in range(args.steps):
            state, loss = step(state, tokens, labels, jax.random.PRNGKey(1))
        final = float(loss)  # chain-wide sync inside the trace window
    dt = (time.perf_counter() - t0) / args.steps
    if final != final:  # NaN
        raise SystemExit("non-finite loss during trace")
    files = glob.glob(os.path.join(args.out, "**", "*"), recursive=True)
    print(json.dumps({
        "platform": dev.platform,
        "trace_dir": args.out,
        "files": len([f for f in files if os.path.isfile(f)]),
        "step_ms": round(dt * 1e3, 2),
        "note": "open with tensorboard --logdir or perfetto; step_ms is "
                "trace-window wall (chained, one sync)",
    }))


if __name__ == "__main__":
    main()
