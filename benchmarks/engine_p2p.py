"""Engine A/B: BASIC vs EPOLL point-to-point latency and throughput.

The BASIC engine grew caller-thread fast paths in round 3 (inline send +
lazy recv); round 4 gives EPOLL its epoll-native equivalent (idle-comm
inline dispatch + immediate IO pass, epoll_engine.cc). This bench measures
what those paths exist for — per-message round-trip latency at small/medium
sizes and sustained throughput at large sizes — for both engines with one
command, so "EPOLL within noise of BASIC" is a number, not a claim.

Method: two spawned processes over `tpunet.transport.Net` on loopback.
For each size: ping-pong (send then recv back) `iters` times, take the
best iteration (kernel-noise floor, nccl-tests convention). Throughput is
unidirectional bytes / (round-trip / 2). Engine is selected via
TPUNET_IMPLEMENT in the child env BEFORE the native lib loads.

1-core caveat (PERF_NOTES.md): both processes share the core, so absolute
GB/s sits below the 2-socket ceiling; the A/B *ratio* is the signal.

Round-5 methodology (verdict item 6): --reps N (default 10) runs N
FRESH process pairs per engine, interleaved A/B/A/B, and reports the
per-size MEDIAN and IQR of each rep's best-of-iters — box-noise drift
(cpu freq, neighbors) hits both engines equally and medians resist the
stragglers, so "within noise" becomes a statement about a distribution,
not a single sample.

Round-6 additions: per-size syscalls/MiB and bytes/syscall, derived from the
native tpunet_engine_syscalls_total{op,dir} counters over the timed window
(telemetry.reset() after warmup). The counter-derived budget is the signal
the 1-core box CANNOT noise out: a change that re-fragments the vectored
wire path (one sendmsg per [payload|crc] chunk, MSG_WAITALL reads) moves
syscalls/MiB by integer factors while GB/s swings ±20% on its own.

Usage: python -m benchmarks.engine_p2p [--sizes 1048576 134217728]
       [--iters 8] [--nstreams 4] [--engines BASIC EPOLL] [--reps 10]
       [--json PATH]
Prints ONE JSON line: {engine: {size: {rtt_ms, rtt_iqr_ms, gbps,
syscalls_per_mib, bytes_per_syscall, ...}}, epoll_over_basic_rtt: {...}}
(medians when reps > 1); --json also writes it to PATH for bench.py-style
file consumption.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _syscall_total() -> int:
    """Sum of tpunet_engine_syscalls_total{op,dir} since the last
    telemetry.reset() — wire send/recv-family syscalls this process issued."""
    from tpunet import telemetry

    return int(sum(telemetry.metrics().get(
        "tpunet_engine_syscalls_total", {}).values()))


def _stream_tx_split() -> dict:
    """Per-stream tx byte shares since the last telemetry.reset() — the
    observable stripe skew (round 9): uniform striping reads ~1/nstreams
    per stream; a weighted/degraded comm reads its actual split."""
    from tpunet import telemetry

    per = {}
    for key, value in telemetry.metrics().get(
            "tpunet_stream_tx_bytes", {}).items():
        lab = telemetry.labels(key)
        if "stream" in lab:
            per[int(lab["stream"])] = int(value)
    total = sum(per.values())
    return {str(s): round(v / total, 4) for s, v in sorted(per.items())} if total else {}


def _shm_stats() -> tuple:
    """(shm_bytes, wakeups) since the last telemetry.reset() — the SHM
    engine lane's bytes/wakeup is the ring's syscalls/MiB analogue."""
    from tpunet import telemetry

    m = telemetry.metrics()
    return (int(sum(m.get("tpunet_shm_bytes_total", {}).values())),
            int(sum(m.get("tpunet_shm_wakeups_total", {}).values())))


def _peer(rank: int, conn, q, engine: str, nstreams: int,
          sizes: list, iters: int) -> None:
    try:
        # "SHM" is the intra-host shared-memory lane: the BASIC engine
        # fronted by the SHM engine (TPUNET_SHM=1) — payloads ride mmap'd
        # ring segments instead of loopback TCP.
        if engine.upper() == "SHM":
            os.environ["TPUNET_IMPLEMENT"] = "BASIC"
            os.environ["TPUNET_SHM"] = "1"
        else:
            os.environ["TPUNET_IMPLEMENT"] = engine
            os.environ["TPUNET_SHM"] = "0"
        os.environ["TPUNET_NSTREAMS"] = str(nstreams)
        import numpy as np

        from tpunet import telemetry
        from tpunet.transport import Net

        net = Net()
        # Rendezvous over this peer's dedicated pipe (parent relays the
        # handles); the queue carries results only — never timing, never
        # rendezvous (tests/test_transport.py pattern).
        listen = net.listen(0)
        conn.send(bytes(listen.handle))
        sc = net.connect(conn.recv())
        rc = listen.accept()

        out = {}
        for size in sizes:
            buf_tx = np.frombuffer(bytes(range(256)) * ((size // 256) + 1),
                                   dtype=np.uint8)[:size].copy()
            buf_rx = np.zeros(size, dtype=np.uint8)
            times = []
            for it in range(2 + iters):  # 2 warmup
                if it == 2:
                    # Counter window starts after warmup: syscalls/MiB below
                    # covers exactly the timed iterations.
                    telemetry.reset()
                t0 = time.perf_counter()
                if rank == 0:
                    sc.send(buf_tx, timeout=120)
                    rc.recv(buf_rx, timeout=120)
                else:
                    rc.recv(buf_rx, timeout=120)
                    sc.send(buf_tx, timeout=120)
                dt = time.perf_counter() - t0
                if it >= 2:
                    times.append(dt)
            if size and not np.array_equal(buf_rx, buf_tx):
                raise RuntimeError(f"payload corrupt at size {size}")
            best = min(times)
            # Syscall budget over the timed window: this process moved
            # size bytes out AND size bytes in per iteration (ping-pong).
            syscalls = _syscall_total()
            moved = 2 * size * iters
            shm_bytes, shm_wakeups = _shm_stats()
            out[size] = {"rtt_ms": round(best * 1e3, 4),
                         "gbps": round(size / (best / 2) / 1e9, 3) if size else None,
                         "syscalls": syscalls,
                         "syscalls_per_mib": (round(syscalls / (moved / 2**20), 3)
                                              if moved else None),
                         "bytes_per_syscall": (round(moved / syscalls)
                                               if syscalls and moved else None),
                         # SHM lane: ring bytes + futex wakes over the window
                         # (bytes/wakeup — the ring's bytes/syscall analogue).
                         "shm_bytes": shm_bytes or None,
                         "bytes_per_wakeup": (round(shm_bytes / shm_wakeups)
                                              if shm_bytes and shm_wakeups
                                              else None),
                         # Per-stream tx byte shares over the timed window —
                         # stripe skew made eyeball-able (round 9).
                         "stream_tx_split": _stream_tx_split()}
        sc.close()
        rc.close()
        listen.close()
        net.close()
        q.put((f"result{rank}", out))
    except Exception as e:  # noqa: BLE001
        q.put((f"result{rank}", f"ERR: {e!r}"))


def run_engine(engine: str, nstreams: int, sizes: list, iters: int) -> dict:
    import multiprocessing as mp

    import queue as queue_mod

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    pipes = [ctx.Pipe() for _ in range(2)]
    procs = [ctx.Process(target=_peer, args=(r, pipes[r][1], q, engine,
                                             nstreams, sizes, iters))
             for r in range(2)]
    for p in procs:
        p.start()
    results = {}
    try:
        # Relay each peer's listen handle to the other (dedicated pipes;
        # the queue is results-only).
        h0 = pipes[0][0].recv()
        h1 = pipes[1][0].recv()
        pipes[0][0].send(h1)
        pipes[1][0].send(h0)
        deadline = time.time() + 600
        while len(results) < 2 and time.time() < deadline:
            try:
                tag, payload = q.get(timeout=max(1, deadline - time.time()))
            except queue_mod.Empty:
                break
            results[tag] = payload
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
    for r, p in enumerate(procs):
        if f"result{r}" not in results:
            raise SystemExit(
                f"{engine} rank {r} died without reporting "
                f"(exitcode {p.exitcode}) — native-layer crash?")
    for tag, payload in results.items():
        if isinstance(payload, str):
            raise SystemExit(f"{engine} {tag} failed: {payload}")
    # Rank 0's clock covers the same round trips; use it.
    return results["result0"]


def main(argv=None) -> None:
    import statistics

    from benchmarks import iqr

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--sizes", type=int, nargs="+",
                    default=[4096, 1 << 20, 128 << 20])
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--nstreams", type=int, default=4)
    ap.add_argument("--engines", nargs="+", default=["BASIC", "EPOLL"])
    ap.add_argument("--reps", type=int, default=10,
                    help="fresh process pairs per engine, interleaved "
                         "A/B/A/B; report per-size median + IQR")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="also write the result object to PATH "
                         "(bench.py-style machine consumption; stdout keeps "
                         "the one-JSON-line contract either way)")
    args = ap.parse_args(argv)

    # Interleaved: rep k runs every engine before rep k+1 starts, so slow
    # drift lands on both sides of every ratio. A flaky rep (native crash,
    # spawn failure) is LOGGED and skipped — at 20 fresh process pairs per
    # session, aborting on one discards a multi-minute run; medians come
    # from the completed reps (chip_session's incremental-persistence
    # philosophy). Zero completed reps for an engine is still fatal.
    raw = {eng: [] for eng in args.engines}
    failures = {eng: 0 for eng in args.engines}
    for rep in range(max(args.reps, 1)):
        for eng in args.engines:
            try:
                r = run_engine(eng, args.nstreams, args.sizes, args.iters)
            except SystemExit as err:
                failures[eng] += 1
                print(f"[engine_p2p] rep {rep} {eng} FAILED: {err}",
                      file=sys.stderr)
                continue
            raw[eng].append(r)
            print(f"[engine_p2p] rep {rep} {eng}: {r}", file=sys.stderr)
    for eng in args.engines:
        if not raw[eng]:
            raise SystemExit(f"{eng}: every rep failed")

    out = {"nstreams": args.nstreams, "reps": args.reps,
           "failed_reps": failures, "engines": {}}
    for eng in args.engines:
        agg = {}
        for s in args.sizes:
            rtts = [r[s]["rtt_ms"] for r in raw[eng]]
            spread = iqr(rtts)
            spm = [r[s]["syscalls_per_mib"] for r in raw[eng]
                   if r[s].get("syscalls_per_mib") is not None]
            bps = [r[s]["bytes_per_syscall"] for r in raw[eng]
                   if r[s].get("bytes_per_syscall") is not None]
            bpw = [r[s]["bytes_per_wakeup"] for r in raw[eng]
                   if r[s].get("bytes_per_wakeup") is not None]
            agg[s] = {
                "rtt_ms": round(statistics.median(rtts), 4),
                "rtt_iqr_ms": round(spread, 4) if spread is not None else None,
                "gbps": (round(s / (statistics.median(rtts) / 1e3 / 2) / 1e9,
                               3) if s else None),
                # Counter-derived fragmentation signal (median over reps):
                # immune to the box's timing noise, so regressions that
                # re-fragment the vectored wire path are visible even when
                # GB/s is not (PERF_NOTES round 6).
                "syscalls_per_mib": (round(statistics.median(spm), 3)
                                     if spm else None),
                "bytes_per_syscall": (round(statistics.median(bps))
                                      if bps else None),
                # SHM lane only: payload bytes per futex wake syscall over
                # the timed window (median over reps; None on TCP lanes and
                # on reps whose window never parked a waiter).
                "bytes_per_wakeup": (round(statistics.median(bpw))
                                     if bpw else None),
                # Last rep's per-stream tx shares (deterministic from the
                # rotation, so any rep is representative).
                "stream_tx_split": raw[eng][-1][s].get("stream_tx_split"),
            }
        out["engines"][eng] = agg
    if "BASIC" in out["engines"] and "EPOLL" in out["engines"]:
        out["epoll_over_basic_rtt"] = {
            str(s): round(out["engines"]["BASIC"][s]["rtt_ms"]
                          / out["engines"]["EPOLL"][s]["rtt_ms"], 3)
            for s in args.sizes
        }
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=2)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
