"""Open-loop serving load harness for the disaggregated tier.

Closed-loop drivers (submit, wait, repeat) let a slow server throttle its
own offered load and hide latency cliffs; this harness is OPEN-LOOP: a
Poisson arrival process fixes the offered request rate no matter how the
fleet is doing, so queueing delay and SLO misses show up instead of
evaporating. The workload is shaped like serving, not like a microbench:

  * **Poisson arrivals** at a fixed rate (exponential inter-arrival gaps).
  * **Heavy-tailed prompt lengths** (lognormal), rounded UP into a small
    set of length buckets — the tail is real but the per-length jit
    retrace count stays bounded (one prefill trace per bucket).
  * **Conversation sessions**: a completed request spawns a follow-up
    with probability `session_prob`, its prompt extending the previous
    prompt with the generated tokens (re-bucketed) — the multi-turn
    arrival correlation single-shot load misses.

Latency comes from the tier's OWN SLO histograms (`tpunet_req_ttft_us`,
`tpunet_req_tpot_us` — the same families Prometheus scrapes), so the
harness measures what operators would see, and goodput-at-SLO is the
conservative joint bound: completed rate scaled by the smaller of the
TTFT / TPOT within-SLO fractions.

`run_load()` is the reusable core (the live weight-swap smoke lane drives
it against a fleet mid-publication: `on_tick(elapsed, pump)` fires every
loop pass and `pump` is a bounded poll/submit step a `publish()` call can
interleave between broadcast chunks). The CLI wires a self-contained
in-process two-tier fleet and prints ONE JSON line.
"""

from __future__ import annotations

import argparse
import json
import math
import time


def bucketize(n: int, buckets) -> int:
    """Smallest bucket >= n, else the largest (the cap keeps the lognormal
    tail from minting unbounded distinct prompt lengths -> retraces)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def hist_quantile(bounds, q: float) -> float:
    """Quantile from cumulative histogram buckets [(le, cum_count), ...]
    (telemetry.histogram_buckets): the smallest upper bound covering
    q of the samples — what a Prometheus `histogram_quantile` would pin
    to bucket resolution. inf when the top bucket holds the quantile."""
    total = bounds[-1][1] if bounds else 0
    if total <= 0:
        return float("nan")
    want = math.ceil(q * total)
    for le, cum in bounds:
        if cum >= want:
            return le
    return float("inf")


def hist_frac_within(bounds, slo_us: float) -> float:
    """Fraction of samples at or under `slo_us`, read CONSERVATIVELY from
    the histogram: the cumulative count at the largest bound <= slo_us
    (samples in a bucket straddling the SLO count as misses)."""
    total = bounds[-1][1] if bounds else 0
    if total <= 0:
        return 0.0
    best = 0
    for le, cum in bounds:
        if le <= slo_us:
            best = cum
    return best / total


def run_load(router, *, duration_s: float, rate: float, vocab: int,
             buckets=(8, 16, 32, 64), new_range=(4, 16),
             session_prob: float = 0.3, tail_sigma: float = 0.8,
             seed: int = 0, slo_ttft_us: float = 1_000_000,
             slo_tpot_us: float = 100_000, on_tick=None,
             drain_timeout: float = 240.0) -> dict:
    """Drive `router` under open-loop Poisson load for `duration_s`, then
    drain, and return the measurement dict (see CLI JSON for the keys).

    The caller owns the fleet and the measurement window: reset telemetry
    after warmup, before calling. `on_tick(elapsed_s, pump)` runs once per
    loop pass; `pump()` is one bounded submit/poll/reap step, safe to call
    from inside a `WeightPublisher.publish(pump=...)` so arrivals keep
    flowing while weight bytes stream."""
    import numpy as np

    from tpunet import telemetry
    from tpunet.serve import RouterBusyError

    rng = np.random.default_rng(seed)
    mean_len = math.exp(tail_sigma ** 2 / 2) * buckets[0] * 1.5

    def draw_prompt(prev=None):
        if prev is None:
            raw = int(rng.lognormal(math.log(mean_len), tail_sigma))
        else:
            raw = len(prev)
        plen = bucketize(max(1, raw), buckets)
        prompt = rng.integers(0, vocab, plen).astype(np.int32)
        if prev is not None:  # conversation turn: extend, re-bucket
            keep = min(len(prev), plen)
            prompt[:keep] = prev[-keep:] if keep < len(prev) else prev
        return prompt

    counts = {"offered": 0, "completed": 0, "rejected": 0, "sessions": 0}
    live: dict[int, dict] = {}   # rid -> {"prompt": ..., "max_new": ...}
    seen: set[int] = set()
    t0 = time.monotonic()
    next_arrival = t0 + float(rng.exponential(1.0 / rate))
    followups: list = []

    def submit(prompt):
        counts["offered"] += 1
        max_new = int(rng.integers(new_range[0], new_range[1] + 1))
        try:
            rid = router.submit(prompt, max_new)
        except RouterBusyError:
            counts["rejected"] += 1  # open loop: backpressure drops, not waits
            return
        live[rid] = {"prompt": prompt, "max_new": max_new}

    def reap():
        for rid, tokens in list(router._results.items()):
            if rid in seen or rid not in live:
                continue
            seen.add(rid)
            counts["completed"] += 1
            rec = live.pop(rid)
            if (rng.random() < session_prob
                    and time.monotonic() - t0 < duration_s):
                counts["sessions"] += 1
                followups.append(np.concatenate(
                    [rec["prompt"], np.asarray(tokens, np.int32)]))

    def pump():
        nonlocal next_arrival
        now = time.monotonic()
        while now >= next_arrival and now - t0 < duration_s:
            submit(draw_prompt())
            next_arrival += float(rng.exponential(1.0 / rate))
        while followups:
            submit(draw_prompt(prev=followups.pop()))
        router.poll()
        reap()

    while time.monotonic() - t0 < duration_s:
        pump()
        if on_tick is not None:
            on_tick(time.monotonic() - t0, pump)
        time.sleep(0.001)
    wall_load = time.monotonic() - t0

    deadline = time.monotonic() + drain_timeout
    while live and time.monotonic() < deadline:
        router.poll()
        reap()
        time.sleep(0.001)
    if live:
        raise TimeoutError(
            f"{len(live)} request(s) never completed within {drain_timeout}s "
            f"after the load window")
    wall_total = time.monotonic() - t0

    parsed = telemetry.metrics()
    ttft = telemetry.histogram_buckets("tpunet_req_ttft_us", parsed)
    tpot = telemetry.histogram_buckets("tpunet_req_tpot_us", parsed)
    ttft_ok = hist_frac_within(ttft, slo_ttft_us)
    tpot_ok = hist_frac_within(tpot, slo_tpot_us) if tpot else 1.0
    return {
        "duration_s": round(wall_load, 3),
        "drain_s": round(wall_total - wall_load, 3),
        "offered_rps": round(counts["offered"] / wall_load, 3),
        "achieved_rps": round(counts["completed"] / wall_total, 3),
        **counts,
        "failed": counts["offered"] - counts["completed"]
                  - counts["rejected"],
        "ttft_p50_us": hist_quantile(ttft, 0.50),
        "ttft_p99_us": hist_quantile(ttft, 0.99),
        "tpot_p99_us": hist_quantile(tpot, 0.99),
        "slo_ttft_us": slo_ttft_us, "slo_tpot_us": slo_tpot_us,
        "ttft_ok_frac": round(ttft_ok, 4),
        "tpot_ok_frac": round(tpot_ok, 4),
        # Conservative joint bound: per-request TTFT/TPOT pairing is not
        # recoverable from the histograms, so goodput charges the worse
        # of the two miss fractions against the whole completed rate.
        "goodput_rps": round(
            min(ttft_ok, tpot_ok) * counts["completed"] / wall_total, 3),
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--platform", default="cpu", choices=["cpu", "tpu"])
    ap.add_argument("--d", type=int, default=32)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--ff", type=int, default=64)
    ap.add_argument("--vocab", type=int, default=64)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--kv-codec", default="int8",
                    help="KV wire codec for the shipped blocks")
    ap.add_argument("--duration", type=float, default=10.0,
                    help="open-loop load window, seconds")
    ap.add_argument("--rate", type=float, default=4.0,
                    help="offered arrival rate, requests/second")
    ap.add_argument("--buckets", default="8,16,32,64",
                    help="prompt-length buckets (heavy tail rounds UP "
                         "into these; caps the retrace count)")
    ap.add_argument("--new-min", type=int, default=4)
    ap.add_argument("--new-max", type=int, default=16)
    ap.add_argument("--session-prob", type=float, default=0.3)
    ap.add_argument("--tail-sigma", type=float, default=0.8,
                    help="lognormal sigma of the raw prompt-length draw")
    ap.add_argument("--slo-ttft-us", type=float, default=1_000_000)
    ap.add_argument("--slo-tpot-us", type=float, default=100_000)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    buckets = tuple(int(b) for b in args.buckets.split(","))

    from benchmarks import reassert_jax_platform

    reassert_jax_platform(args.platform)
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpunet import serve, telemetry
    from tpunet.models import Transformer

    model = Transformer(
        vocab=args.vocab, d_model=args.d, n_layers=args.layers,
        n_heads=args.heads, d_ff=args.ff,
        compute_dtype=jnp.bfloat16 if args.platform == "tpu"
        else jnp.float32)
    toks = jax.random.randint(jax.random.PRNGKey(0), (2, buckets[0]), 0,
                              args.vocab)
    params = model.init(jax.random.PRNGKey(1), toks)["params"]
    max_len = buckets[-1] + args.new_max

    lsock = serve.Router.listen("127.0.0.1:0")
    addr = "127.0.0.1:%d" % lsock.getsockname()[1]

    def decode_main():
        worker = serve.connect_decode(addr, model, params, slots=args.slots,
                                      max_len=max_len,
                                      kv_codec=args.kv_codec)
        try:
            worker.serve()
        finally:
            worker.close()

    th = threading.Thread(target=decode_main, daemon=True)
    th.start()
    router = serve.Router(
        serve.PrefillEngine(model, params, max_len=max_len),
        kv_codec=args.kv_codec)
    router.accept_ranks(lsock, 1)
    lsock.close()
    try:
        # Warm every prompt-length bucket (one prefill + decode trace
        # each), then reset so compile time stays out of the histograms.
        for b in buckets:
            router.submit(np.zeros(b, np.int32), 2)
        router.run(timeout=240)
        telemetry.reset()
        out = run_load(
            router, duration_s=args.duration, rate=args.rate,
            vocab=args.vocab, buckets=buckets,
            new_range=(args.new_min, args.new_max),
            session_prob=args.session_prob, tail_sigma=args.tail_sigma,
            seed=args.seed, slo_ttft_us=args.slo_ttft_us,
            slo_tpot_us=args.slo_tpot_us)
        router.run(timeout=60)  # clear the slate before shutdown
    finally:
        router.shutdown()
        th.join(timeout=60)
        router.close()
    print(json.dumps({
        "platform": jax.devices()[0].platform, "slots": args.slots,
        "kv_codec": args.kv_codec, "rate": args.rate,
        "buckets": list(buckets), "session_prob": args.session_prob,
        **out}))


if __name__ == "__main__":
    main()
