"""Single-chip model-tier headline: Transformer tokens/s + MFU, VGG16 img/s.

The reference's end-to-end validation was a real-hardware model benchmark
(reference README.md:52-84: VGG16 synthetic img/s on V100s); this module is
that tier for the TPU build, run by bench.py on the real chip. MFU uses the
analytic transformer FLOP count (6N per token for the matmuls + 12*L*S*d
for attention scores/values, Chinchilla-appendix convention, embedding
lookup excluded) against the chip's peak bf16 FLOP/s by device kind.

Prints ONE JSON line:
  {"platform": "tpu"|"cpu", "device_kind": str, "tokens_per_s": N,
   "mfu": N|null, "vgg_img_per_s": N}

CPU fallback (TPU tunnel down) uses a smaller config and mfu=null — the
numbers are then smoke-level, flagged by platform="cpu".
"""

from __future__ import annotations

import argparse
import json
import re

# Exact device-kind -> peak bf16 FLOP/s per chip. jax reports kinds like
# "TPU v4", "TPU v5 lite", "TPU v5p", "TPU v6 lite"; _peak_for normalizes
# by stripping the "TPU " prefix and lowercasing, then requires an EXACT
# match — substring matching silently misreported future variants (round-2
# advisor finding). Unknown kind -> None -> mfu=null, which is honest.
# Public numbers: v4 275T, v5e 197T, v5p 459T, v6e 918T.
PEAK_FLOPS = {
    "v2": 45e12 / 2,  # per-chip kind reports a 2-core board on v2/v3
    "v3": 123e12 / 2,
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def transformer_flops_per_token(n_params: int, vocab: int, d_model: int,
                                n_layers: int, seq: int) -> float:
    """Analytic train-step FLOPs per token: 6*N over the matmul params
    (embedding table excluded — a lookup, not a matmul; lm_head included)
    + attention 12*L*S*d_model (QK^T and PV, fwd+bwd). Chinchilla-appendix
    convention; shared with benchmarks.mfu_sweep so the sweep scores with
    exactly the headline's accounting."""
    n_matmul = n_params - vocab * d_model
    return 6 * n_matmul + 12 * n_layers * seq * d_model


def _peak_for(kind: str) -> float | None:
    k = kind.lower().strip()
    if k.startswith("tpu"):
        k = k[3:].strip()
    if k in PEAK_FLOPS:
        return PEAK_FLOPS[k]
    # Tunneled chips suffix a tile index ("v5 lite0") — retry with the
    # trailing integer run stripped. Only on a lookup miss, so a kind that
    # legitimately ends in a digit ("v4") is never mangled.
    return PEAK_FLOPS.get(re.sub(r"\d+$", "", k).strip())


def transformer_bench(on_tpu: bool, attn: str = "flash",
                      block_q: int = 128, block_k: int = 128,
                      remat_policy: str | None = None) -> tuple[float, float | None]:
    """Returns (tokens_per_s, mfu|None). bf16 + `attn` attention on TPU —
    bench.py passes attn="reference" when the flash kernel smoke failed,
    so one broken kernel costs its fallback's speed, not the whole chip.
    block_q/block_k/remat_policy let a chip_session sweep win be applied
    to the headline measurement itself (defaults = the round-3 config)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from benchmarks import chained_step_time
    from tpunet.models import Transformer
    from tpunet.train import create_train_state, make_train_step

    if on_tpu:
        # Sized to one v5e-class chip (benchmarks.mfu_sweep results in
        # PERF_NOTES.md): ~735M params + f32 adamw fills most of HBM under
        # donation; measured 0.41 MFU with flash + remat. The swept
        # alternatives — batch 16 (0.40), L16 and d4096 (both OOM) — lost.
        cfg = dict(vocab=32000, d_model=2048, n_layers=12, n_heads=16, d_ff=8192)
        batch, seq = 8, 2048
        dtype = jnp.bfloat16
        remat = True
    else:  # smoke-size: one CPU core must finish in seconds
        cfg = dict(vocab=512, d_model=64, n_layers=2, n_heads=4, d_ff=128)
        batch, seq = 2, 128
        dtype = jnp.float32
        attn = "reference"
        remat = False

    model = Transformer(compute_dtype=dtype, attn_impl=attn, remat=remat,
                        remat_policy=remat_policy if remat else None,
                        flash_block_q=block_q, flash_block_k=block_k, **cfg)
    tx = optax.adamw(3e-4)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg["vocab"], (batch, seq)), jnp.int32)
    labels = jnp.roll(tokens, -1, axis=1)
    state, _ = create_train_state(model, jax.random.PRNGKey(0), tokens, tx)
    # donate=True is the real-training memory profile — without it the chip
    # must hold two optimizer states and the chip-sized config OOMs.
    step = make_train_step(model, tx)

    dt = chained_step_time(step, state, (tokens, labels, jax.random.PRNGKey(1)),
                           warmup=2, iters=8 if on_tpu else 5)
    tokens_per_s = batch * seq / dt

    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    flops_per_token = transformer_flops_per_token(
        n_params, cfg["vocab"], cfg["d_model"], cfg["n_layers"], seq)
    flops_per_step = flops_per_token * batch * seq
    kind = jax.devices()[0].device_kind
    peak = _peak_for(kind) if on_tpu else None
    mfu = (flops_per_step / dt / peak) if peak else None
    return tokens_per_s, mfu


def vgg_bench(on_tpu: bool) -> float:
    """VGG16 synthetic img/s — the reference's own end-to-end workload."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from tpunet.models import vgg16
    from tpunet.train import create_train_state, make_train_step, synthetic_batch

    if on_tpu:
        model = vgg16(num_classes=1000)
        batch, size = 64, 224
    else:
        from tpunet.models import VGG

        model = VGG(cfg=(8, "M", 16, "M"), num_classes=16, hidden=64)
        batch, size = 8, 32

    tx = optax.sgd(1e-2, momentum=0.9)
    rng = np.random.default_rng(0)
    images, labels = synthetic_batch(rng, batch, size, 1000 if on_tpu else 16)
    images, labels = jnp.asarray(images), jnp.asarray(labels)
    state, _ = create_train_state(model, jax.random.PRNGKey(0), images, tx)
    step = make_train_step(model, tx)

    from benchmarks import chained_step_time

    dt = chained_step_time(step, state, (images, labels, jax.random.PRNGKey(1)),
                           warmup=2, iters=8 if on_tpu else 5)
    return batch / dt


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--platform", choices=["tpu", "cpu"], required=True)
    ap.add_argument("--attn", choices=["flash", "reference"], default="flash",
                    help="attention impl for the TPU transformer tier "
                         "(bench.py passes reference when the flash smoke fails)")
    ap.add_argument("--block-q", type=int, default=128,
                    help="flash tile sizes — apply a chip_session sweep win")
    ap.add_argument("--block-k", type=int, default=128)
    ap.add_argument("--remat-policy", default=None,
                    choices=["dots", "dots_no_batch"],
                    help="selective remat policy for the headline model")
    args = ap.parse_args(argv)

    if args.platform == "cpu":
        from benchmarks import reassert_jax_platform

        reassert_jax_platform("cpu")
    import jax

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if args.platform == "tpu" and not on_tpu:
        raise SystemExit(f"requested tpu, got {dev.platform}")

    tokens_per_s, mfu = transformer_bench(
        on_tpu, args.attn, block_q=args.block_q, block_k=args.block_k,
        remat_policy=args.remat_policy)
    img_per_s = vgg_bench(on_tpu)
    print(json.dumps({
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "attn": args.attn if on_tpu else "reference",
        "tokens_per_s": round(tokens_per_s, 1),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "vgg_img_per_s": round(img_per_s, 2),
        # Tuning fields only when they were actually APPLIED: the CPU
        # fallback and attn=reference never touch flash tiles, and the CPU
        # config runs remat=False — reporting them there would label a
        # measurement with knobs it never used.
        **({"block_q": args.block_q, "block_k": args.block_k}
           if (on_tpu and args.attn == "flash"
               and (args.block_q, args.block_k) != (128, 128)) else {}),
        **({"remat_policy": args.remat_policy}
           if (on_tpu and args.remat_policy) else {}),
    }))


if __name__ == "__main__":
    main()
