"""JAX-tier AllReduce sweep: `dcn_psum` inside jit over the tpunet transport.

BASELINE config 2 ("JAX pmap(lax.psum)-style AllReduce sweep 8 B - 128 MB
over the new DCN transport"): measures the full path a training step pays —
jitted program -> XLA FFI custom call (zero-copy; round 5) -> ring
collectives -> multi-stream engine — vs `benchmarks.busbw_sweep --op
allreduce`, which measures the native collectives alone; the difference is
the JAX-integration tax. --no-ffi forces the legacy io_callback bridge
(the round-4 path: ~3 full-buffer staging copies per call) for A/B.

    python -m benchmarks.psum_sweep -n 2 --nstreams 4 -b 1K -e 64M
"""

from __future__ import annotations

import argparse
import os
import sys
import time

from benchmarks import spawn_ranks
from benchmarks.busbw_sweep import make_table_emitter, parse_size, sweep_sizes


def _worker(rank, world, port, q, args):
    try:
        from benchmarks import reassert_jax_platform

        reassert_jax_platform("cpu")  # loopback ranks cannot share one TPU
        os.environ["TPUNET_NSTREAMS"] = str(args.nstreams)
        if args.no_ffi:
            os.environ["TPUNET_FFI_COLLECTIVES"] = "0"
        import jax
        import jax.numpy as jnp

        from tpunet import distributed
        from tpunet.interop import dcn_psum

        distributed.initialize(f"127.0.0.1:{port}", rank, world)
        fn = jax.jit(dcn_psum)
        rows = []
        for nbytes in sweep_sizes(args.begin, args.end, args.factor):
            count = max(nbytes // 4, 1)
            x = jnp.full((count,), float(rank + 1), jnp.float32)
            iters = args.iters if nbytes >= (1 << 16) else args.iters * 4
            comm = distributed.global_communicator()
            for _ in range(args.warmup):
                fn(x).block_until_ready()
            comm.barrier()
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn(x)
            out.block_until_ready()
            # Closing barrier before reading the clock, matching the
            # busbw_sweep baseline loop — the reported delta between the two
            # IS the JAX-integration tax, so methodology must match.
            comm.barrier()
            dt = (time.perf_counter() - t0) / iters
            expect = float(sum(r + 1 for r in range(world)))
            assert float(out[0]) == expect, f"bad psum result {out[0]} != {expect}"
            rows.append((count * 4, count, dt))
        distributed.finalize()
        q.put((rank, ("OK", rows)))
    except Exception as e:  # noqa: BLE001
        q.put((rank, (f"FAIL: {type(e).__name__}: {e}", [])))


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--world", type=int, default=2)
    ap.add_argument("--nstreams", type=int, default=4)
    ap.add_argument("-b", "--begin", type=parse_size, default=8)
    ap.add_argument("-e", "--end", type=parse_size, default=128 << 20)
    ap.add_argument("-f", "--factor", type=int, default=2)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--warmup", type=int, default=1)
    ap.add_argument("--json", default="", help="also dump rows to this file")
    ap.add_argument("--no-ffi", action="store_true",
                    help="force the io_callback bridge instead of the "
                         "zero-copy XLA FFI custom call (A/B baseline)")
    args = ap.parse_args(argv)

    from benchmarks import check_rank_results

    results = check_rank_results(
        spawn_ranks(_worker, args.world, extra_args=(args,), timeout=3600)
    )
    emit = make_table_emitter("psum", nstreams=args.nstreams, json_path=args.json)
    emit(results[0], args.world)


if __name__ == "__main__":
    main(sys.argv[1:])
