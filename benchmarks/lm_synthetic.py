"""Transformer synthetic training benchmark (tokens/s).

The long-context companion to `benchmarks.vgg_synthetic`: times the jitted
Transformer train step (fwd+bwd+update) on synthetic token batches and
reports tokens/s mean ± std. Exercises the parallelism axes end-to-end:

  Single process: dp×sp×mdl mesh over local devices — ring attention over
  `sp` (context length scales with devices), Megatron TP over `mdl`.
      python -m benchmarks.lm_synthetic --seq 2048 --sp 2 --tp 2
  Multi-process (-n N): per-rank local step + cross-host DCN gradient tier
  (ring allreduce over the multi-stream transport).
      python -m benchmarks.lm_synthetic -n 2 --layers 2 --d-model 128
"""

from __future__ import annotations

import argparse
import math
import os
import statistics
import sys
import time


def _build(args):
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tpunet.models import Transformer, transformer_partition_rules
    from tpunet.parallel import make_named_mesh, replicated, shard_params
    from tpunet.train import (TrainState, create_train_state,
                              create_zero_train_state, make_train_step,
                              make_zero_train_step)

    use_mesh = args.sp > 1 or args.tp > 1
    mesh = None
    if use_mesh:
        n = len(jax.devices())
        dp = max(1, n // (args.sp * args.tp))
        mesh = make_named_mesh({"dp": dp, "sp": args.sp, "mdl": args.tp})

    model = Transformer(
        vocab=args.vocab, d_model=args.d_model, n_layers=args.layers,
        n_heads=args.heads, d_ff=4 * args.d_model, n_experts=args.experts,
        moe_top_k=args.moe_top_k, capacity_factor=args.capacity_factor,
        compute_dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
        attn_impl=("zigzag" if args.zigzag else "ring") if args.sp > 1
        else "reference",
        mesh=mesh, tp_axis="mdl" if args.tp > 1 else None,
    )
    tx = optax.adamw(3e-4)
    rng = np.random.default_rng(0)
    toks = rng.integers(0, args.vocab, size=(args.batch_size, args.seq))
    tokens = jnp.asarray(toks, jnp.int32)
    labels = jnp.roll(tokens, -1, axis=1)
    if args.zigzag:
        from tpunet.parallel import to_zigzag

        # The whole pipeline runs in zigzag sequence order; labels are
        # next-token in NATURAL order, permuted the same way.
        tokens = to_zigzag(tokens, args.sp)
        labels = to_zigzag(labels, args.sp)
    if args.zero:
        if not args.cross_host:
            raise SystemExit("--zero requires --cross-host (it shards the "
                             "optimizer over the DCN world)")
        if args.bucket_bytes is not None:
            raise SystemExit("--bucket-bytes applies to the all-reduce path; "
                             "the ZeRO path syncs via reduce-scatter/all-gather "
                             "(refusing to silently benchmark the wrong path)")
        state, _ = create_zero_train_state(model, jax.random.PRNGKey(0), tokens, tx)
    else:
        state, _ = create_train_state(model, jax.random.PRNGKey(0), tokens, tx)

    if mesh is not None:
        rules = transformer_partition_rules(
            tp_axis="mdl" if args.tp > 1 else None, ep_axis=None
        )
        params = jax.device_put(state.params, shard_params(state.params, mesh, rules))
        opt_state = jax.tree.map(
            lambda leaf: jax.device_put(leaf, replicated(mesh)), state.opt_state
        )
        state = TrainState(params, opt_state, jax.device_put(state.step, replicated(mesh)))
        data_sh = NamedSharding(mesh, P("dp", "sp"))
        tokens = jax.device_put(tokens, data_sh)
        labels = jax.device_put(labels, data_sh)

    if args.zero:
        step = make_zero_train_step(model, tx, donate=True,
                                    fused_xent_block=args.fused_xent,
                                    accum_steps=args.accum)
    else:
        # Passed through unguarded: make_train_step rejects bucket_bytes
        # without cross_host, which is better than silently benchmarking the
        # wrong path.
        step = make_train_step(model, tx, cross_host=args.cross_host, donate=True,
                               bucket_bytes=args.bucket_bytes,
                               fused_xent_block=args.fused_xent,
                               accum_steps=args.accum)
    return state, step, tokens, labels, mesh


def run_benchmark(args, emit=print):
    import contextlib

    import jax

    state, step, tokens, labels, mesh = _build(args)
    rngkey = jax.random.PRNGKey(1)
    ctx = mesh if mesh is not None else contextlib.nullcontext()
    with ctx:
        loss = None
        for _ in range(args.warmup):
            state, loss = step(state, tokens, labels, rngkey)
        if loss is not None:
            loss.block_until_ready()
        rates = []
        tokens_per_batch = args.batch_size * args.seq
        for it in range(args.iters):
            t0 = time.perf_counter()
            for _ in range(args.batches_per_iter):
                state, loss = step(state, tokens, labels, rngkey)
            loss.block_until_ready()
            dt = time.perf_counter() - t0
            rates.append(tokens_per_batch * args.batches_per_iter / dt)
            emit(f"Iter #{it}: {rates[-1]:.0f} tokens/sec")
    if not math.isfinite(float(loss)):
        raise RuntimeError("non-finite loss during benchmark")
    return rates


def _mp_worker(rank, world, port, q, argv):
    try:
        from benchmarks import reassert_jax_platform

        reassert_jax_platform("cpu")  # loopback ranks cannot share one TPU
        args = _parse(argv)
        from tpunet import distributed

        distributed.initialize(f"127.0.0.1:{port}", rank, world)
        args.cross_host = True
        rates = run_benchmark(args, emit=lambda *_: None)
        distributed.finalize()
        q.put((rank, ("OK", rates)))
    except Exception as e:  # noqa: BLE001
        q.put((rank, (f"FAIL: {type(e).__name__}: {e}", [])))


def _parse(argv):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--world", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=8, help="per-process")
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--d-model", type=int, default=512)
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--experts", type=int, default=0)
    ap.add_argument("--moe-top-k", type=int, default=1,
                    help="experts per token (2 = GShard/Mixtral routing); "
                         "the model already scales expert capacity by k")
    ap.add_argument("--capacity-factor", type=float, default=1.25)
    ap.add_argument("--sp", type=int, default=1, help="sequence-parallel axis size")
    ap.add_argument("--zigzag", action="store_true",
                    help="balanced causal context parallelism (zigzag layout) "
                         "instead of the contiguous ring; requires --sp > 1")
    ap.add_argument("--tp", type=int, default=1, help="tensor-parallel axis size")
    ap.add_argument("--bf16", action="store_true", default=True)
    ap.add_argument("--no-bf16", dest="bf16", action="store_false")
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--batches-per-iter", type=int, default=3)
    ap.add_argument("--cross-host", action="store_true")
    ap.add_argument("--accum", type=int, default=None, metavar="K",
                    help="gradient accumulation over K microbatches (batch "
                         "size must divide by K)")
    ap.add_argument("--fused-xent", type=int, default=None, metavar="BLOCK",
                    help="blockwise fused cross-entropy with this vocab block "
                         "size (never materializes the full logits tensor)")
    ap.add_argument("--zero", action="store_true",
                    help="ZeRO-1: shard optimizer state over the DCN world "
                         "(reduce-scatter grads, all-gather params)")
    ap.add_argument("--bucket-bytes", type=int, default=None,
                    help="multi-rank only: nonblocking bucketed gradient sync "
                         "(overlaps DCN transfer with backward); bytes per bucket")
    return ap.parse_args(argv)


def main(argv=None):
    args = _parse(argv)
    need = args.sp * args.tp
    if args.zigzag and args.sp <= 1:
        # Validated BEFORE any worker spawns: a SystemExit inside a spawned
        # worker escapes its `except Exception` reporter and would leave the
        # parent blocking on the result queue instead of printing this.
        raise SystemExit("--zigzag requires --sp > 1 (it is the balanced "
                         "causal layout for sequence parallelism)")
    if args.world > 1 and need > 1:
        # Loopback ranks are single-device; silently downgrading sp/tp would
        # report tokens/s for a configuration the user didn't ask for.
        raise SystemExit(
            "--sp/--tp (in-process mesh axes) apply to single-process mode; "
            "with -n, each rank is one device and parallelism is cross-host DP"
        )
    flags = os.environ.get("XLA_FLAGS", "")
    if (os.environ.get("JAX_PLATFORMS") == "cpu" and need > 1
            and "--xla_force_host_platform_device_count" not in flags):
        # CPU smoke runs of the sp/tp mesh need virtual devices, and the
        # flag must be set before the first jax import.
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={max(8, need)}".strip()
        )
    if args.world == 1:
        from benchmarks import reassert_jax_platform

        reassert_jax_platform()  # the world>1 parent never runs JAX
    if args.world > 1:
        from benchmarks import check_rank_results, spawn_ranks

        results = check_rank_results(spawn_ranks(
            _mp_worker, args.world, extra_args=(argv or sys.argv[1:],), timeout=3600
        ))
        per_rank = [results[r] for r in range(args.world)]
        totals = [sum(it) for it in zip(*per_rank)]
        mean, std = statistics.mean(totals), statistics.pstdev(totals)
        print(f"Tokens/sec per rank: {mean / args.world:.0f}")
        print(f"Total tokens/sec on {args.world} rank(s): {mean:.0f} +-{1.96 * std:.0f}")
    else:
        rates = run_benchmark(args)
        mean, std = statistics.mean(rates), statistics.pstdev(rates)
        print(f"Tokens/sec: {mean:.0f} +-{1.96 * std:.0f}")


if __name__ == "__main__":
    main()
