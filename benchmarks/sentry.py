"""CI perf-regression sentry: counter-gated claims vs a checked-in baseline.

The repo's perf story is carried by COUNTERS, not wall clocks: syscalls/MiB
(vectored wire path), codec wire ratio (bf16 halves ring bytes), schedule
step counts (ring = 2(W-1) wire rounds), and the hier DCN byte split
(inter-host TCP vs intra-host SHM). Those numbers are deterministic or
near-deterministic on any box, so a regression in one is a code change,
not CI weather — unlike GB/s, which swings ±20% on the shared-core runner.

This sentry replays every claim in ``docs/SENTRY_BASELINE.json`` against a
fresh measurement and fails CI on a VERIFIED regression: a claim that
fails a live measurement is re-measured once before the verdict, so a
single scheduling hiccup (the busbw floor is the only wall-clock-adjacent
claim) cannot red a PR. Canned measurements (``--measurements``) skip the
re-measure — that is the deterministic test vehicle (tests/test_sentry.py
proves the sentry goes red on an inflated fixture baseline).

Baseline schema (``tpunet-sentry-v1``)::

    {"schema": "tpunet-sentry-v1",
     "claims": {
       "<key>": {"max": 3.0, "desc": "..."}     # measured <= max
       "<key>": {"min": 0.02, ...}               # measured >= min
       "<key>": {"equals": 6, ...}               # measured == equals exactly
     }}

Usage::

    python -m benchmarks.sentry --measure [--out PATH]
    python -m benchmarks.sentry --check [--baseline PATH]
                                        [--measurements PATH] [--json PATH]

``--measure`` prints (and optionally writes) the measurement dict — run it
after an intentional perf change, then update the baseline's margins by
hand (the baseline is a reviewed artifact, never auto-written). ``--check``
exits nonzero on a verified regression and prints one verdict line per
claim.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

BASELINE_DEFAULT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "docs", "SENTRY_BASELINE.json")

ENGINE_SIZE = 16 << 20
CODEC_SIZE = 4 << 20
STEPS_WORLD = 4
STEPS_SIZE = 1 << 20
HIER_WORLD = 4
HIER_SIZE = 4 << 20

# Which measurement keys each measurement group produces: a failing claim
# re-measures ONLY its group (a full re-run would double the lane's cost).
GROUPS = {
    "engines": ("basic_syscalls_per_mib", "epoll_syscalls_per_mib",
                "basic_busbw_gbps"),
    "codec": ("codec_wire_ratio_bf16_over_f32",),
    "steps": ("ring_steps_w4",),
    "hier": ("hier_dcn_fraction_w4",),
}


def _codec_rank(rank, world, port, q, codec):
    try:
        os.environ.update({"TPUNET_WIRE_DTYPE": codec,
                           "TPUNET_NSTREAMS": "1",
                           "TPUNET_ASYNC_CHANNELS": "1",
                           "TPUNET_ALGO": "ring"})
        import numpy as np

        from tpunet import telemetry
        from tpunet.collectives import Communicator

        with Communicator(f"127.0.0.1:{port}", rank, world) as comm:
            arr = np.full(CODEC_SIZE // 4, float(rank + 1), np.float32)
            comm.all_reduce(arr, inplace=True)  # warmup: wiring + scratch
            comm.barrier()
            telemetry.reset()
            comm.all_reduce(arr, inplace=True)
            wire = int(sum(
                telemetry.metrics()["tpunet_isend_nbytes_sum"].values()))
        q.put((rank, ("OK", wire)))
    except Exception as e:  # noqa: BLE001
        q.put((rank, (f"ERR: {e!r}", 0)))


def _steps_rank(rank, world, port, q):
    try:
        os.environ.update({"TPUNET_NSTREAMS": "1",
                           "TPUNET_ASYNC_CHANNELS": "1",
                           "TPUNET_ALGO": "ring"})
        import numpy as np

        from tpunet import telemetry
        from tpunet.collectives import Communicator

        with Communicator(f"127.0.0.1:{port}", rank, world) as comm:
            arr = np.full(STEPS_SIZE // 4, float(rank + 1), np.float32)
            comm.all_reduce(arr)  # warmup
            comm.barrier()
            telemetry.reset()
            comm.all_reduce(arr)
            m = telemetry.metrics()
        ring = sum(int(v) for key, v in
                   m.get("tpunet_coll_steps_total", {}).items()
                   if telemetry.labels(key)["algo"] == "ring")
        q.put((rank, ("OK", ring)))
    except Exception as e:  # noqa: BLE001
        q.put((rank, (f"ERR: {e!r}", 0)))


def _hier_rank(rank, world, port, q):
    try:
        os.environ.update({
            "TPUNET_NSTREAMS": "1", "TPUNET_ASYNC_CHANNELS": "1",
            "TPUNET_SHM": "1",
            "TPUNET_HOST_ID": f"sentryhost{rank // 2}",  # hosts [0,0,1,1]
        })
        import numpy as np

        from tpunet import telemetry
        from tpunet.collectives import Communicator

        with Communicator(f"127.0.0.1:{port}", rank, world,
                          algo="hier") as comm:
            arr = np.full(HIER_SIZE // 4, float(rank + 1), np.float32)
            comm.all_reduce(arr)  # warmup: wires SHM rings + mesh
            comm.barrier()
            telemetry.reset()
            comm.all_reduce(arr)
            m = telemetry.metrics()
        # DCN proxy: TCP tx bytes; intra-host traffic rides the separate
        # SHM byte family, so the split is exact (test_schedules pattern).
        tcp_tx = sum(int(v) for key, v in
                     m.get("tpunet_qos_bytes_total", {}).items()
                     if telemetry.labels(key)["dir"] == "tx")
        shm_tx = sum(int(v) for key, v in
                     m.get("tpunet_shm_bytes_total", {}).items()
                     if telemetry.labels(key)["dir"] == "tx")
        q.put((rank, ("OK", (tcp_tx, shm_tx))))
    except Exception as e:  # noqa: BLE001
        q.put((rank, (f"ERR: {e!r}", (0, 0))))


def measure_group(group: str) -> dict:
    """One measurement group -> {measurement key: value}."""
    from benchmarks import check_rank_results, spawn_ranks

    if group == "engines":
        from benchmarks.engine_p2p import run_engine

        out = {}
        r = run_engine("BASIC", nstreams=2, sizes=[ENGINE_SIZE], iters=4)
        out["basic_syscalls_per_mib"] = r[ENGINE_SIZE]["syscalls_per_mib"]
        out["basic_busbw_gbps"] = r[ENGINE_SIZE]["gbps"]
        r = run_engine("EPOLL", nstreams=2, sizes=[ENGINE_SIZE], iters=4)
        out["epoll_syscalls_per_mib"] = r[ENGINE_SIZE]["syscalls_per_mib"]
        return out
    if group == "codec":
        wire = {}
        for codec in ("f32", "bf16"):
            results = check_rank_results(spawn_ranks(
                _codec_rank, 2, extra_args=(codec,), timeout=180))
            wire[codec] = results[0]
        ratio = (wire["bf16"] / wire["f32"]) if wire["f32"] else float("inf")
        return {"codec_wire_ratio_bf16_over_f32": round(ratio, 4)}
    if group == "steps":
        results = check_rank_results(spawn_ranks(
            _steps_rank, STEPS_WORLD, timeout=180))
        # Every rank of a ring allreduce runs the same 2(W-1) wire rounds;
        # report the MAX so any rank's deviation is the headline number.
        return {"ring_steps_w4": max(results.values())}
    if group == "hier":
        results = check_rank_results(spawn_ranks(
            _hier_rank, HIER_WORLD, timeout=180))
        tcp = sum(t for t, _ in results.values())
        shm = sum(s for _, s in results.values())
        frac = tcp / (tcp + shm) if (tcp + shm) else 1.0
        return {"hier_dcn_fraction_w4": round(frac, 4)}
    raise ValueError(f"unknown measurement group {group!r}")


def measure(groups=None) -> dict:
    out = {}
    for g in groups or GROUPS:
        out.update(measure_group(g))
    return out


def _violation(claim: dict, value) -> str | None:
    """None when the claim holds, else a human-readable violation."""
    if value is None:
        return "no measurement"
    if "max" in claim and value > claim["max"]:
        return f"{value} > max {claim['max']}"
    if "min" in claim and value < claim["min"]:
        return f"{value} < min {claim['min']}"
    if "equals" in claim and value != claim["equals"]:
        return f"{value} != {claim['equals']}"
    return None


def check(baseline: dict, measurements: dict | None = None,
          remeasure: bool = True) -> dict:
    """Verdict per claim. With live measurements (measurements=None), a
    failing claim's group is re-measured ONCE before it counts as a
    verified regression. Returns {"ok": bool, "claims": {key: {"value",
    "verdict", "detail"}}}."""
    if baseline.get("schema") != "tpunet-sentry-v1":
        raise ValueError(
            f"baseline schema {baseline.get('schema')!r} is not "
            f"tpunet-sentry-v1")
    claims = baseline.get("claims", {})
    key_group = {k: g for g, keys in GROUPS.items() for k in keys}
    live = measurements is None
    if live:
        groups = sorted({key_group[k] for k in claims if k in key_group})
        measurements = measure(groups)
    out = {"ok": True, "claims": {}}
    for key, claim in claims.items():
        value = measurements.get(key)
        why = _violation(claim, value)
        if why is not None and live and remeasure and key in key_group:
            print(f"[sentry] {key}: {why} — re-measuring once to verify",
                  file=sys.stderr)
            measurements.update(measure_group(key_group[key]))
            value = measurements.get(key)
            why = _violation(claim, value)
            if why is not None:
                why += " (verified on re-measure)"
        verdict = "ok" if why is None else "REGRESSION"
        out["claims"][key] = {"value": value, "verdict": verdict,
                              "detail": why or ""}
        if why is not None:
            out["ok"] = False
    out["measurements"] = measurements
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m benchmarks.sentry", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    mode = ap.add_mutually_exclusive_group(required=True)
    mode.add_argument("--measure", action="store_true",
                      help="run every measurement group and print the dict")
    mode.add_argument("--check", action="store_true",
                      help="replay baseline claims; exit 1 on a verified "
                           "regression")
    ap.add_argument("--baseline", default=BASELINE_DEFAULT,
                    help="claims file (default docs/SENTRY_BASELINE.json)")
    ap.add_argument("--measurements", default=None,
                    help="canned measurement JSON: check against these "
                         "instead of measuring (deterministic test vehicle; "
                         "disables the re-measure pass)")
    ap.add_argument("--out", default=None,
                    help="--measure: also write the dict to this path")
    ap.add_argument("--json", default=None,
                    help="--check: also write the verdict object here")
    args = ap.parse_args(argv)

    os.environ.setdefault("TPUNET_CRC", "0")
    if args.measure:
        m = measure()
        print(json.dumps(m, indent=2))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(m, f, indent=2)
        return 0

    with open(args.baseline) as f:
        baseline = json.load(f)
    canned = None
    if args.measurements:
        with open(args.measurements) as f:
            canned = json.load(f)
    verdict = check(baseline, canned)
    for key, c in verdict["claims"].items():
        detail = f" ({c['detail']})" if c["detail"] else ""
        print(f"[sentry] {c['verdict']:>10}  {key} = {c['value']}{detail}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(verdict, f, indent=2)
    if not verdict["ok"]:
        print("sentry: VERIFIED perf regression — see claims above",
              file=sys.stderr)
        return 1
    print("sentry OK: every baseline claim holds")
    return 0


if __name__ == "__main__":
    sys.exit(main())
