"""Shared multiprocess launch harness for the benchmark entrypoints."""

from __future__ import annotations

import math
import multiprocessing as mp
import os
import socket
import time


def chained_step_time(step_fn, state, args, warmup: int, iters: int) -> float:
    """Per-step seconds for a `state, loss = step_fn(state, *args)` train
    step, measured by threading `state` through `iters` chained steps and
    syncing ONCE on the final loss.

    Per-step `jax.block_until_ready` timing is wrong on the tunneled TPU
    platform bench runs use: block_until_ready returns before the device
    finishes (measured: a 75 ms matmul chain "completed" in 78 µs), which
    inflated throughput >10×. A host transfer of the loss — which depends on
    every step in the chain through `state` — is the only sync the platform
    honors, and paying it once over the chain also amortizes per-dispatch
    tunnel latency the way real training loops do. Compatible with donated
    (`donate=True`) train steps, unlike repeated calls on one state.
    """
    for _ in range(max(warmup, 1)):
        state, loss = step_fn(state, *args)
    if not math.isfinite(float(loss)):  # hard sync: warmup/compile complete
        raise RuntimeError("non-finite loss in benchmark warmup")
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = step_fn(state, *args)
    final = float(loss)  # single chain-wide sync
    dt = (time.perf_counter() - t0) / iters
    if not math.isfinite(final):
        raise RuntimeError("non-finite loss in benchmark")
    return dt


def flash_smoke_ok(kernels) -> bool:
    """True only for a kernel smoke that ran ON the chip and passed the
    core flash kernels — a CPU-fallback smoke trivially passes in interpret
    mode and proves nothing about Mosaic; a parity failure is just as
    disqualifying as a crash. Shared by bench.py and chip_session so the
    smoke's key contract lives in one place."""
    return (isinstance(kernels, dict)
            and kernels.get("platform") == "tpu"
            and kernels.get("flash_fwd") == "ok"
            and kernels.get("flash_bwd") == "ok")


def run_json_lines(argv: list, timeout_s: float,
                   cwd: str | None = None) -> tuple[list, str]:
    """Run `python <argv...>` and parse every JSON-object line it printed.

    Returns (rows, "") on success or ([], error-tail) when the tool timed
    out, exited nonzero, or printed no JSON. Shared by bench.py (one-line
    tools) and benchmarks.chip_session (mfu_sweep prints one line per
    config) so the subprocess/timeout/parse contract cannot drift.
    """
    import json
    import subprocess
    import sys

    try:
        p = subprocess.run([sys.executable] + list(argv), capture_output=True,
                           text=True, timeout=timeout_s, cwd=cwd)
    except subprocess.TimeoutExpired:
        return [], f"timed out after {timeout_s}s"
    rows = []
    if p.returncode == 0 and p.stdout.strip():
        for line in p.stdout.strip().splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    if not rows:
        return [], (p.stderr or "no JSON output")[-500:]
    return rows, ""


def reassert_jax_platform(platform: str | None = None) -> None:
    """Make JAX_PLATFORMS actually win: an axon-style sitecustomize pins
    jax_platforms via jax.config at interpreter start, so the env var alone
    cannot select CPU (and a down TPU tunnel would hang the run). Call
    before any jax use; no-op when neither `platform` nor the env is set."""
    platform = platform or os.environ.get("JAX_PLATFORMS")
    if not platform:
        return
    os.environ["JAX_PLATFORMS"] = platform
    import jax

    jax.config.update("jax_platforms", platform)


def iqr(xs) -> float | None:
    """Interquartile range, np.percentile linear-interpolation definition
    — THE one definition every benchmark reports (serve_bench, engine_p2p,
    bench.py), so cross-bench IQR columns are comparable. None when fewer
    than 4 samples (a 'spread' of 2-3 points is noise about noise)."""
    import numpy as np

    if len(xs) < 4:
        return None
    return float(np.percentile(xs, 75) - np.percentile(xs, 25))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def check_rank_results(results: dict) -> dict:
    """For workers posting (status, payload): raise if any rank failed,
    else return {rank: payload}. Shared by the benchmark entrypoints."""
    for rank, (status, _) in sorted(results.items()):
        if status != "OK":
            raise SystemExit(f"rank {rank} failed: {status}")
    return {rank: payload for rank, (_, payload) in results.items()}


def spawn_ranks(target, world: int, extra_args=(), timeout: float = 600.0) -> dict:
    """Spawn `world` processes running target(rank, world, port, queue, *extra).

    Each worker must post (rank, payload) to the queue exactly once. Returns
    {rank: payload}. Workers are always joined/killed, even if a rank dies
    without reporting (a native-layer crash posts nothing).
    """
    import queue as queue_mod

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    port = free_port()
    procs = [
        ctx.Process(target=target, args=(r, world, port, q) + tuple(extra_args))
        for r in range(world)
    ]
    for p in procs:
        p.start()
    results: dict = {}
    try:
        for _ in range(world):
            try:
                rank, payload = q.get(timeout=timeout)
            except queue_mod.Empty:
                break  # diagnosed below with exit codes, not a raw traceback
            results[rank] = payload
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()
                p.join()  # reap, so exitcode below reads -SIGKILL, not None
    if len(results) < world:
        missing = sorted(set(range(world)) - results.keys())
        codes = {r: procs[r].exitcode for r in missing}
        raise SystemExit(
            f"ranks {missing} never reported within {timeout}s "
            f"(exit codes {codes}) — native-layer crash or hang?")
    return results
