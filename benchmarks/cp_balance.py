"""Context-parallel load balance — mechanical schedule accounting.

The zigzag layout's "~2x causal critical-path cut" is a claim about WORK
DISTRIBUTION across ranks, and on a lockstep ring that distribution is
trace-time structure — it can be computed exactly, with no hardware at
all. This module does that accounting for the contiguous and zigzag ring
schedules (tpunet/parallel/ring_attention.py, zigzag_attention.py), under
two cost models:

  "executed" — what the kernels actually run, the wall-clock-proportional
      model. A dispatched block executes its FULL dense einsums whether it
      is unmasked or diagonal (switched_block_update's diag branch masks
      inside a full-size einsum; only the skip branch computes nothing).
      The contiguous tier dispatches whole shard-blocks (2x2 chunks = 4
      units when not skipped); the zigzag tier dispatches chunk-blocks
      (1 unit each when not skipped).
  "flops" — useful (unmasked) FLOPs: full chunk-block = 1, diagonal = 0.5,
      masked = 0. Identical for both layouts in total (same causal mask,
      sliced differently); the model an idealized diagonal kernel that
      skips its masked half would execute.

  rank work = units rank i computes across the W ring steps
  critical  = sum over steps of the SLOWEST rank's units that step — every
      step ends in a ppermute barrier, so on real multi-chip hardware
      (ranks in parallel) wall-clock tracks the "executed" critical path.
      The 1-chip sandbox serializes ranks, so it can only ever observe the
      TOTAL — this accounting is the evidence the sandbox cannot produce.

Closed forms (pinned in tests/test_cp_balance.py): contiguous executes
rank totals 4(i+1) with critical path 4W (no rank skips its own diagonal
step, and it dispatches dense); zigzag executes exactly 2 chunk-units per
rank per step plus 1 extra on its diagonal step — totals 2W+1, critical
2W+1, balanced to within that single unit. Executed cut = 4W/(2W+1):
1.6x at W=2, 1.78x at W=4, approaching 2x from below. The useful-FLOP
accounting gives (4W-2)/2W = 2 - 1/W with zigzag perfectly balanced.

Prints ONE JSON line with per-rank tables, critical paths, and ratios for
the requested world sizes, both cost models.
"""

from __future__ import annotations

import argparse
import json

COSTS = ("executed", "flops")


def chunk_flops(q_chunk: int, k_chunk: int) -> float:
    """Useful-FLOP units of q-chunk attending k-chunk under the causal
    mask: 1 = full (strictly past), 0.5 = diagonal, 0 = fully masked. The
    chunk-granular restatement of ring_attention.causal_block_mode."""
    if k_chunk < q_chunk:
        return 1.0
    return 0.5 if k_chunk == q_chunk else 0.0


def layout_chunks(world: int, layout: str) -> list[tuple[int, int]]:
    """The 2W half-shard chunks rank i holds: contiguous pairs (2i, 2i+1)
    or the zigzag stripe pair (i, 2W-1-i) of zigzag_chunk_order."""
    if layout == "contiguous":
        return [(2 * i, 2 * i + 1) for i in range(world)]
    if layout == "zigzag":
        return [(i, 2 * world - 1 - i) for i in range(world)]
    raise ValueError(layout)


def _rank_step_units(world: int, layout: str, i: int, s: int,
                     cost: str) -> float:
    """Units rank i runs while holding rank s's K/V shard."""
    chunks = layout_chunks(world, layout)
    flops = [(a, b, chunk_flops(a, b))
             for a in chunks[i] for b in chunks[s]]
    if cost == "flops":
        return sum(f for _, _, f in flops)
    if cost != "executed":
        raise ValueError(cost)
    if layout == "contiguous":
        # One shard-granular dispatch: causal_block_mode full/diag both
        # execute the dense 2x2-chunk block; only skip executes nothing.
        return 4.0 if any(f > 0 for _, _, f in flops) else 0.0
    # Zigzag dispatches per chunk-block quadrant: full and diag branches
    # both execute the dense c x c block, skip executes nothing.
    return sum(1.0 for _, _, f in flops if f > 0)


def step_work(world: int, layout: str,
              cost: str = "executed") -> list[list[float]]:
    """[rank][step] -> units. Ring step t hands rank i the K/V of rank
    (i - t) % world — the same `src` rotation both ring tiers scan over."""
    return [
        [_rank_step_units(world, layout, i, (i - t) % world, cost)
         for t in range(world)]
        for i in range(world)
    ]


def summarize(world: int, layout: str, cost: str = "executed") -> dict:
    per_step = step_work(world, layout, cost)
    rank_totals = [sum(row) for row in per_step]
    critical = sum(max(per_step[i][t] for i in range(world))
                   for t in range(world))
    return {
        "rank_work_units": rank_totals,
        "total_units": sum(rank_totals),
        "critical_path_units": critical,
        "slowest_over_mean": round(
            max(rank_totals) / (sum(rank_totals) / world), 4),
    }


def compare(world: int, cost: str = "executed") -> dict:
    cont = summarize(world, "contiguous", cost)
    zig = summarize(world, "zigzag", cost)
    return {
        "world": world,
        "cost": cost,
        "contiguous": cont,
        "zigzag": zig,
        # The multi-chip wall-clock claim, stated as schedule structure:
        # lockstep critical path, contiguous over zigzag.
        "critical_path_cut": round(
            cont["critical_path_units"] / zig["critical_path_units"], 4),
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--worlds", nargs="+", type=int, default=[2, 4, 8, 32])
    ap.add_argument("--cost", choices=COSTS + ("both",), default="both")
    args = ap.parse_args(argv)
    costs = COSTS if args.cost == "both" else (args.cost,)
    print(json.dumps({
        "metric": "cp_causal_critical_path",
        "unit": "chunk-block units (c = S/2W)",
        "comparisons": [compare(w, c) for c in costs for w in args.worlds],
    }))


if __name__ == "__main__":
    main()
