"""One-command TPU measurement session — run the moment the tunnel is up.

The axon tunnel flaps for hours; when it comes back it may not stay long.
This driver runs every on-chip measurement the round needs, IN PRIORITY
ORDER, each in its own killable subprocess with a hard timeout, and
persists results INCREMENTALLY — after every step it rewrites
``benchmarks/tpu_measured.json`` (the file bench.py replays when the
tunnel is down) with everything captured so far, stamped with the current
HEAD commit. A tunnel death mid-session therefore keeps all completed
measurements; re-running resumes the full list.

Priority order (round-4 verdict):
  1. kernel_smoke        — all flash kernel variants on real Mosaic (gate)
  2. tpu_headline        — tokens/s + MFU + VGG img/s at the headline shape
  3. decode_bench x10    — MHA, GQA (kv4), window, speculative
                           (gamma 2/4/8 + per-row), int8+quant-draft, and
                           the TTFT prefill pair (reference vs flash
                           kernel at p=4096)
  4. mfu_attribution     — per-segment breakdown of the headline step
  5. block sweep s2048   — flash tile grid at the headline seq
  6. block sweep s8192   — flash tile grid at long context
  7. mfu_sweep 5         — long-context s8192 MFU (fused-xent config)
  8. mfu_sweep 7         — remat_policy="dots" A/B at the headline shape

Usage: python -m benchmarks.chip_session [--only 1 2 3] [--skip-probe]
Writes benchmarks/tpu_measured.json + benchmarks/chip_session_raw.json.
Prints one summary JSON line at the end.
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MEASURED = os.path.join(REPO, "benchmarks", "tpu_measured.json")
RAW = os.path.join(REPO, "benchmarks", "chip_session_raw.json")


def _run_json(argv: list[str], timeout_s: int) -> tuple[dict | None, str]:
    """Collect every JSON line the tool printed (shared helper): single-line
    tools return that object; multi-line tools (mfu_sweep prints one line
    per config) return {"rows": [...]}."""
    from benchmarks import run_json_lines

    rows, err = run_json_lines(argv, timeout_s, cwd=REPO)
    if not rows:
        return None, err
    return (rows[0] if len(rows) == 1 else {"rows": rows}), ""


def _head_commit() -> str:
    p = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                       capture_output=True, text=True, cwd=REPO)
    return p.stdout.strip() or "unknown"


def _now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


# (key, argv, timeout_s) — priority order. Generous timeouts: first compile
# of the chip-sized model takes minutes over the tunnel.
STEPS: list[tuple[str, list[str], int]] = [
    ("kernels", ["-m", "benchmarks.kernel_smoke"], 900),
    ("headline", ["-m", "benchmarks.tpu_headline", "--platform", "tpu"], 2400),
    ("decode_mha", ["-m", "benchmarks.decode_bench", "--platform", "tpu",
                    "--d", "2048", "--layers", "12", "--heads", "16",
                    "--ff", "8192", "--batch", "8", "--prompt", "512",
                    "--new", "256"], 1800),
    ("decode_gqa", ["-m", "benchmarks.decode_bench", "--platform", "tpu",
                    "--d", "2048", "--layers", "12", "--heads", "16",
                    "--ff", "8192", "--batch", "8", "--prompt", "512",
                    "--new", "256", "--kv-heads", "4"], 1800),
    ("decode_window", ["-m", "benchmarks.decode_bench", "--platform", "tpu",
                       "--d", "2048", "--layers", "12", "--heads", "16",
                       "--ff", "8192", "--batch", "8", "--prompt", "512",
                       "--new", "256", "--window", "256"], 1800),
    ("decode_spec", ["-m", "benchmarks.decode_bench", "--platform", "tpu",
                     "--d", "2048", "--layers", "12", "--heads", "16",
                     "--ff", "8192", "--batch", "8", "--prompt", "512",
                     "--new", "256", "--spec-gamma", "4",
                     "--draft-layers", "2"], 2400),
    # Gamma sweep (round-4 verdict item 4: "report ... tok/s vs plain
    # decode at the headline shape for gamma in {2,4,8}"): same shape and
    # draft as decode_spec, the speculative depth alone varies.
    ("decode_spec_g2", ["-m", "benchmarks.decode_bench", "--platform",
                        "tpu", "--d", "2048", "--layers", "12", "--heads",
                        "16", "--ff", "8192", "--batch", "8", "--prompt",
                        "512", "--new", "256", "--spec-gamma", "2",
                        "--draft-layers", "2"], 2400),
    ("decode_spec_g8", ["-m", "benchmarks.decode_bench", "--platform",
                        "tpu", "--d", "2048", "--layers", "12", "--heads",
                        "16", "--ff", "8192", "--batch", "8", "--prompt",
                        "512", "--new", "256", "--spec-gamma", "8",
                        "--draft-layers", "2"], 2400),
    # Per-row (continuous-commit) speculative at the same shape: the
    # lockstep-vs-per-row half of the verdict table (decode_quant covers
    # per-row + int8 draft; this isolates per-row with the fp draft).
    ("decode_spec_per_row", ["-m", "benchmarks.decode_bench", "--platform",
                             "tpu", "--d", "2048", "--layers", "12",
                             "--heads", "16", "--ff", "8192", "--batch",
                             "8", "--prompt", "512", "--new", "256",
                             "--spec-gamma", "4", "--draft-layers", "2",
                             "--spec-per-row"], 2400),
    ("decode_quant", ["-m", "benchmarks.decode_bench", "--platform", "tpu",
                      "--d", "2048", "--layers", "12", "--heads", "16",
                      "--ff", "8192", "--batch", "8", "--prompt", "512",
                      "--new", "256", "--quant", "int8", "--spec-gamma", "4",
                      "--spec-draft", "quant", "--spec-per-row"], 2400),
    # Time-to-first-token pair: long prompt, few new tokens. The flash
    # variant routes the empty-cache prefill through the Mosaic kernel
    # (O(p) score memory, K/V streamed at kv-head width); the reference
    # variant pays the p x p reference einsum with a materialized GQA
    # repeat. Same GQA shape otherwise.
    ("prefill_ttft_ref", ["-m", "benchmarks.decode_bench", "--platform",
                          "tpu", "--d", "2048", "--layers", "12", "--heads",
                          "16", "--ff", "8192", "--batch", "2", "--prompt",
                          "4096", "--new", "16", "--kv-heads", "4"], 1800),
    ("prefill_ttft_flash", ["-m", "benchmarks.decode_bench", "--platform",
                            "tpu", "--d", "2048", "--layers", "12",
                            "--heads", "16", "--ff", "8192", "--batch", "2",
                            "--prompt", "4096", "--new", "16", "--kv-heads",
                            "4", "--attn", "flash"], 1800),
    ("attribution", ["-m", "benchmarks.mfu_attribution"], 2400),
    ("block_sweep_s2048", ["-m", "benchmarks.mfu_attribution",
                           "--sweep-blocks", "--blocks", "128", "256", "512"],
     1800),
    ("block_sweep_s8192", ["-m", "benchmarks.mfu_attribution",
                           "--sweep-blocks", "--seq", "8192", "--batch", "2",
                           "--blocks", "128", "256", "512"], 1800),
    ("longctx_s8192", ["-m", "benchmarks.mfu_sweep", "5"], 2400),
    ("remat_dots_ab", ["-m", "benchmarks.mfu_sweep", "0", "7"], 2400),
    # Continuous batching vs lockstep ON CHIP: the regime the component
    # exists for — step compute runs on the TPU while the host absorbs and
    # refills (pipeline=2 keeps a window in flight), so the dispatch
    # overhead that dominates the single-core CPU toy hides under device
    # time. GQA kv4 = the serving cache regime.
    ("serve", ["-m", "benchmarks.serve_bench", "--platform", "tpu",
               "--d", "2048", "--layers", "12", "--heads", "16",
               "--ff", "8192", "--vocab", "32000", "--kv-heads", "4",
               "--slots", "4", "--requests", "12", "--prompt", "256",
               "--new-min", "32", "--new-max", "128",
               "--steps-per-call", "16", "--pipeline", "2",
               "--reps", "3"], 2400),
]


def _persist(raw: dict, launch_dirty=None) -> None:
    """Atomically write the resume log AND refresh the distilled measured
    file — the one persistence path both the step loop and the tuned pass
    use. Provenance recorded alongside the results: the STEPS fingerprint
    (so a later edit to a step's argv — batch size, seq, flags — can't
    silently reuse results measured under the old parameters) and any
    uncommitted edits to the measured paths (so a bare commit hash never
    misrepresents a dirty-tree measurement). `launch_dirty` carries dirt
    observed when steps LAUNCHED — an edit present at launch is what the
    subprocess imported and measured, even if reverted before the step
    finished; persist-time sampling alone would record it clean."""
    dirty = sorted(set(_dirty_measured_paths()) | set(launch_dirty or ()))
    rec = {"commit": _head_commit(), "measured_at": _now(),
           "step_fps": _step_fingerprints(), "results": raw}
    if dirty:
        rec["dirty"] = dirty
    with open(RAW + ".tmp", "w") as f:
        json.dump(rec, f, indent=2)
    os.replace(RAW + ".tmp", RAW)
    _write_measured(raw, dirty)


# The tuned-pass argv template (sweep winner's tiles substituted in) and the
# failed-smoke fallback flags — lifted to constants so the fingerprint can
# cover EVERY argv this module launches, including the two built outside
# STEPS in main().
TUNED_HEADLINE_ARGV = ["-m", "benchmarks.tpu_headline", "--platform", "tpu",
                       "--block-q", "{bq}", "--block-k", "{bk}"]
ATTN_FALLBACK_FLAGS = ["--attn", "reference"]


def _fp(obj) -> str:
    import hashlib

    return hashlib.sha256(
        json.dumps(obj, sort_keys=True).encode()).hexdigest()[:16]


def _step_fingerprints() -> dict:
    """PER-STEP hashes of every measurement parameter this module can
    launch, keyed by result key. Timeouts are excluded — a timeout bump is
    pure orchestration and must not discard a session — and so is the
    step LIST itself: adding a new step must not invalidate the other
    steps' cached results (the round-4 global fingerprint did exactly
    that). The headline's hash folds in the smoke-fallback flags (they
    rewrite its argv when the smoke fails); headline_tuned is keyed by the
    tuned-pass template."""
    argvs = {k: a for k, a, _ in STEPS}
    fps = {k: _fp(a) for k, a in argvs.items()}
    fps["headline"] = _fp([argvs["headline"], ATTN_FALLBACK_FLAGS])
    # The tuned headline derives from the s2048 sweep's winner, so its
    # cache validity depends on the sweep's parameters too (and the tuned
    # pass itself re-checks the crowned tiles against the cached result).
    fps["headline_tuned"] = _fp([TUNED_HEADLINE_ARGV,
                                 argvs["block_sweep_s2048"]])
    return fps


def _dirty_measured_paths() -> list[str]:
    """Uncommitted (incl. untracked) files under the measurement-validity
    paths. Undecidable (git failure) records an explicit sentinel — a
    failure must not block persisting results, but it must not record
    clean provenance either (the sentinel blocks resume and is surfaced in
    the measured file like any dirty entry)."""
    import bench

    dirty = bench._dirty_paths(
        bench.MEASURED_PATHS + bench.SESSION_SCRIPT_PATHS, repo=REPO)
    return dirty if dirty is not None else ["<undecidable: git status failed>"]


def _tpu_alive(timeout_s: int = 90) -> bool:
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices()[0]; print(d.platform)"],
            capture_output=True, text=True, timeout=timeout_s)
        return p.returncode == 0 and p.stdout.strip() == "tpu"
    except subprocess.TimeoutExpired:
        return False


def _write_measured(raw: dict, dirty: list[str] | None = None) -> None:
    """Distill the raw session results into the bench.py replay file. Only
    fields actually measured are written — a partial session yields a
    partial but HONEST measured file (bare commit hash, no prose claims).
    A session with NOTHING real captured (all steps errored) writes
    nothing, so a dead tunnel can't clobber the previous good file; the
    first real write this session backs the old file up alongside."""
    if not any(isinstance(v, dict) and "error" not in v for v in raw.values()):
        return
    out: dict = {
        "measured_at": _now(),
        "measured_commit": _head_commit(),
        "platform": "tpu",
    }
    if dirty:
        # The hash alone would misrepresent a dirty-tree measurement.
        out["uncommitted_at_measurement"] = dirty
    head = raw.get("headline") or {}
    if head.get("platform") == "tpu":
        out.update({
            "device_kind": head.get("device_kind"),
            "attn": head.get("attn"),
            "tokens_per_s": head.get("tokens_per_s"),
            "mfu": head.get("mfu"),
            "vgg_img_per_s": head.get("vgg_img_per_s"),
            "config": (f"d2048 L12 ff8192 h16, batch 8 x seq 2048, bf16 + "
                       f"{head.get('attn', 'flash')} + remat, donated adamw; "
                       "chained timing (benchmarks.chained_step_time)"),
        })
    if isinstance(raw.get("kernels"), dict) and "error" not in raw["kernels"]:
        out["kernels"] = {k: v for k, v in raw["kernels"].items()
                          if k != "platform"}
        out["kernels_platform"] = raw["kernels"].get("platform")
    tuned = raw.get("headline_tuned")
    if (isinstance(tuned, dict) and "error" not in tuned
            and tuned.get("platform") == "tpu"):
        out["headline_tuned"] = tuned
    decode = {}
    for key in ("decode_mha", "decode_gqa", "decode_window", "decode_spec",
                "decode_spec_g2", "decode_spec_g8", "decode_spec_per_row",
                "decode_quant", "prefill_ttft_ref", "prefill_ttft_flash"):
        d = raw.get(key)
        if isinstance(d, dict) and d.get("platform") == "tpu":
            decode[key] = {k: d[k] for k in
                           ("decode_tok_s", "wall_s", "kv_heads", "window",
                            "batch", "prompt", "new", "attn", "quant",
                            "speculative") if k in d}
    if decode:
        out["decode"] = decode
    if (isinstance(raw.get("attribution"), dict)
            and "error" not in raw["attribution"]):
        a = raw["attribution"]
        out["attribution"] = {k: a.get(k) for k in
                              ("segments", "full_step_ms", "mfu",
                               "expected_full_ms", "residual_ms")}
    for key in ("block_sweep_s2048", "block_sweep_s8192", "longctx_s8192",
                "remat_dots_ab", "serve"):
        if isinstance(raw.get(key), dict) and "error" not in raw[key]:
            out[key] = raw[key]
    out["note"] = ("Captured by benchmarks.chip_session while the tunnel "
                   "was up; bench.py replays this file (with a mechanical "
                   "staleness stamp) when the tunnel is down at bench time.")
    if os.path.exists(MEASURED):
        # Back up the previous file whenever this write changes its
        # provenance or loses measured fields — "same commit" does NOT
        # imply "same provenance" (a dirty-tree partial re-run at the same
        # commit must not silently clobber a clean complete session).
        try:
            with open(MEASURED) as f:
                prev = json.load(f)
            volatile = {"measured_at", "staleness", "note"}
            if (prev.get("measured_commit") != out["measured_commit"]
                    or prev.get("uncommitted_at_measurement")
                    != out.get("uncommitted_at_measurement")
                    or (set(prev) - set(out) - volatile)):
                with open(MEASURED.replace(".json", "_prev.json"), "w") as f:
                    json.dump(prev, f, indent=2)
                    f.write("\n")
        except (OSError, ValueError):
            pass
    tmp = MEASURED + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f, indent=2)
        f.write("\n")
    os.replace(tmp, MEASURED)


def _wanted_attn(key: str, cmd: list) -> str | None:
    """The attention impl a step WANTS: flash for the headline (its
    default; demotion appends the fallback flags) or whatever --attn
    names; None when the step has no attn axis to check."""
    if key == "headline":
        return "flash"
    if "--attn" in cmd:
        return cmd[cmd.index("--attn") + 1]
    return None


def _cache_satisfies(want_attn: str | None, cached) -> bool:
    """A cached result is reusable iff it is error-free AND ran with the
    attn the step wants — a demoted (reference-fallback) run must not
    satisfy a flash step forever once the smoke recovers."""
    if not (isinstance(cached, dict) and "error" not in cached):
        return False
    return want_attn is None or cached.get("attn") == want_attn


def _resumable_results(prev: dict) -> dict:
    """The subset of a prior session's results a fresh run may reuse.

    Commit-hash equality was the round-4 first cut, but it discards a whole
    session the moment ANY commit lands — including the commit that records
    the session's own measurements. Three checks replace it:
    - session-wide: the prior session's tree was clean over the measured
      paths (results measured with uncommitted kernel edits are
      unreproducible — the edit may since have been reverted with no diff
      to show for it), and bench.py's staleness check over the measured
      code paths + step scripts reads clean; `stale is None` (bad commit,
      git failure or timeout) means provenance is undecidable — no resume,
      re-measure;
    - per-step: the step's recorded argv fingerprint matches the current
      one (a parameter edit — batch, seq, flags — invalidates THAT step
      only; adding a new step or editing another step's argv leaves it
      cached; a legacy raw file without fingerprints never resumes)."""
    import bench

    if prev.get("dirty"):
        return {}
    st = bench._measurement_staleness(
        prev.get("commit"),
        paths=bench.MEASURED_PATHS + bench.SESSION_SCRIPT_PATHS)
    if st.get("stale") is not False:
        return {}
    prev_fps = prev.get("step_fps") or {}
    now_fps = _step_fingerprints()
    return {k: v for k, v in (prev.get("results") or {}).items()
            if prev_fps.get(k) and prev_fps.get(k) == now_fps.get(k)}


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--only", nargs="+", type=int,
                    help="1-based step indices to run (default: all)")
    ap.add_argument("--skip-probe", action="store_true")
    args = ap.parse_args(argv)

    if not args.skip_probe and not _tpu_alive():
        print(json.dumps({"error": "TPU tunnel down — nothing measured"}))
        raise SystemExit(1)

    raw: dict = {}
    if os.path.exists(RAW):
        try:
            with open(RAW) as f:
                prev = json.load(f)
            raw = _resumable_results(prev)
        except (OSError, ValueError):
            pass

    which = (set(args.only) if args.only
             else set(range(1, len(STEPS) + 1)))
    status: dict = {}
    launch_dirty: set = set()  # dirt observed at any step launch, sticky
    for i, (key, cmd, timeout_s) in enumerate(STEPS, start=1):
        if i not in which:
            continue
        want_attn = _wanted_attn(key, cmd)
        if _cache_satisfies(want_attn, raw.get(key)):
            status[key] = "cached"
            continue
        # A previously DEMOTED result (smoke failed that session, the step
        # ran with reference attention) must not satisfy a flash-wanting
        # step forever — drop it and let this session's gate decide.
        raw.pop(key, None)
        print(f"[chip_session] {i}/{len(STEPS)} {key} ...", file=sys.stderr)
        if want_attn == "flash":
            # Same per-kernel degradation bench.py applies, decided BEFORE
            # the run (a parity-failing kernel completes without crashing —
            # its numbers must never be published as flash): anything short
            # of an on-chip all-ok smoke — parity failure, errored/timed-out
            # smoke, or a smoke skipped via --only — drops the step to
            # reference attention, exactly like bench.py's gate. To measure
            # flash, run the smoke step in the same session. The demotion
            # targets the --attn value specifically; the step's output JSON
            # echoes the attn that RAN.
            from benchmarks import flash_smoke_ok

            if not flash_smoke_ok(raw.get("kernels")):
                print(f"[chip_session]   flash smoke not ok (or not run); "
                      f"{key} uses reference attention", file=sys.stderr)
                if key == "headline":
                    cmd = cmd + ATTN_FALLBACK_FLAGS
                else:
                    cmd = list(cmd)
                    cmd[cmd.index("--attn") + 1] = "reference"
        # Sample dirt at LAUNCH: the subprocess imports the tree as it is
        # now — an edit reverted mid-step must still taint this session.
        launch_dirty |= set(_dirty_measured_paths())
        out, err = _run_json(cmd, timeout_s)
        if out is None:
            raw[key] = {"error": err}
            status[key] = f"FAILED: {err[:120]}"
        else:
            raw[key] = out
            status[key] = "ok"
        # Persist after EVERY step: a tunnel death loses nothing captured.
        _persist(raw, launch_dirty)
        print(f"[chip_session]   {key}: {status[key]}", file=sys.stderr)

    # Apply-the-sweep pass: if the s2048 block sweep crowned a non-default
    # tile config, re-measure the headline WITH it — the sweep exists to
    # move the headline number, not to sit in a table. Scoped like a
    # follow-on of the sweep step (skipped under an --only that excludes
    # it); a previously-errored attempt is retried like any other step.
    from benchmarks import flash_smoke_ok as _fso

    sweep_step = next(i for i, (k, _, _) in enumerate(STEPS, start=1)
                      if k == "block_sweep_s2048")
    bs = raw.get("block_sweep_s2048")
    best = bs.get("best") if isinstance(bs, dict) else None
    tuned_prev = raw.get("headline_tuned")
    # A cached tuned headline is valid only while its tiles ARE the sweep's
    # current winner — a re-run sweep that crowns different tiles (or
    # reverts to the default) voids it, or the published tuned number
    # would contradict the sweep table sitting next to it.
    if (isinstance(tuned_prev, dict) and "error" not in tuned_prev and best
            and f"bq{tuned_prev.get('block_q')}_bk{tuned_prev.get('block_k')}"
            != best):
        raw.pop("headline_tuned")
        tuned_prev = None
        _persist(raw, launch_dirty)
    if (sweep_step in which
            and _fso(raw.get("kernels"))  # tuned tiles ARE flash tiles —
            # never publish a tuned flash headline past a failed smoke
            and best and best != "bq128_bk128"
            and (tuned_prev is None or "error" in tuned_prev)):
        m = re.match(r"bq(\d+)_bk(\d+)", best)
        if m:
            print(f"[chip_session] re-measuring headline with swept blocks "
                  f"{bs['best']} ...", file=sys.stderr)
            launch_dirty |= set(_dirty_measured_paths())
            out, err = _run_json(
                [arg.format(bq=m.group(1), bk=m.group(2))
                 for arg in TUNED_HEADLINE_ARGV], 2400)
            raw["headline_tuned"] = out if out is not None else {"error": err}
            status["headline_tuned"] = ("ok" if out is not None
                                        else f"FAILED: {err[:120]}")
            _persist(raw, launch_dirty)

    print(json.dumps({"commit": _head_commit(), "status": status,
                      "measured_file": MEASURED}))


if __name__ == "__main__":
    main()
