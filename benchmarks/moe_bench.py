"""MoE dispatch/combine microbenchmark: latency-class dispatch vs bulk tenant.

The workload the QoS machinery was built for, finally driving it
(docs/DESIGN.md "Workloads: MoE dispatch & pipeline stages"): W spawned
expert-parallel ranks each run

  * a LATENCY-class communicator carrying Zipf-skewed (--skew /
    TPUNET_MOE_SKEW) MoE dispatch+combine typed AllToAlls
    (tpunet.workloads.moe), and
  * a concurrent BULK-class communicator flooding gradient-sized
    AllReduces,

with the process-wide DRR wire gate armed (TPUNET_QOS_INFLIGHT_BYTES
wire=...). Claims ride counters, never wall-clock (the PR 3/5 stance):

  * latency-class p99 wire-credit queue wait bounded (--p99-budget-us,
    default the 100 ms bucket) while the bulk tenant moves its FULL byte
    budget — both read from tpunet_qos_queue_wait_us /
    tpunet_qos_bytes_total;
  * dispatch wire bytes per stage from tpunet_a2a_bytes_total (under a
    2x2 TPUNET_HOST_ID split + --a2a hier, the DCN bytes are exactly the
    inter-stage figure);
  * dropped-token fraction from the dispatcher (capacity overflow is
    visible, never silent).

`--check` asserts the gates; tests/moe_smoke.py is the CI twin.

Run:
  python -m benchmarks.moe_bench --world 4 --check --json
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _p99_queue_wait_us(metrics, cls):
    from tpunet import telemetry

    buckets = []
    for key, value in metrics.get("tpunet_qos_queue_wait_us_bucket", {}).items():
        lab = telemetry.labels(key)
        if lab.get("class") != cls:
            continue
        le = lab["le"]
        buckets.append((float("inf") if le == "+Inf" else float(le), int(value)))
    buckets.sort()
    if not buckets or buckets[-1][1] == 0:
        return None
    total = buckets[-1][1]
    for bound, cum in buckets:
        if cum >= 0.99 * total:
            return bound
    return float("inf")


def _rank_main(rank, world, ports, q, args):
    try:
        os.environ.update({
            "TPUNET_NSTREAMS": "1",
            "TPUNET_ASYNC_CHANNELS": "1",
            "TPUNET_QOS_INFLIGHT_BYTES": f"wire={args.wire}",
            "TPUNET_MOE_SKEW": str(args.skew),
        })
        if args.fake_hosts > 1:
            os.environ["TPUNET_SHM"] = "1"
            os.environ["TPUNET_HOST_ID"] = f"moehost{rank // (world // args.fake_hosts)}"
        if args.a2a:
            os.environ["TPUNET_A2A_ALGO"] = args.a2a
        import numpy as np

        from tpunet import telemetry
        from tpunet.collectives import Communicator
        from tpunet.workloads import moe

        lat = Communicator(f"127.0.0.1:{ports[0]}", rank, world,
                           wire_dtype=args.wire_dtype, traffic_class="latency")
        blk = Communicator(f"127.0.0.1:{ports[1]}", rank, world,
                           traffic_class="bulk")
        rng = np.random.default_rng(123 + rank)
        disp = moe.MoeDispatcher(lat, d_model=args.d_model, capacity=args.capacity)
        grad = np.full(args.bulk_bytes // 4, 0.5, np.float32)

        # Warmup both paths (wires meshes, SHM rings, channels), then reset.
        disp.dispatch(rng.standard_normal((8, args.d_model)).astype(np.float32),
                      moe.route_tokens(8, world, args.skew, rng))
        disp.combine(np.zeros((world, args.capacity, args.d_model), np.float32))
        blk.all_reduce(np.ones(1024, np.float32))
        lat.barrier()
        telemetry.reset()

        stop = threading.Event()
        bulk_iters = [0]

        def bulk_loop():
            while not stop.is_set():
                blk.all_reduce(grad, inplace=True)
                bulk_iters[0] += 1

        bt = threading.Thread(target=bulk_loop, daemon=True)
        bt.start()
        # Fixed step count: dispatch/combine are COLLECTIVES, so every rank
        # must run the same number (a wall-clock-bounded loop desyncs the
        # ranks and reads as a peer death).
        lat_us = []
        steps = 0
        for _ in range(args.steps):
            toks = rng.standard_normal((args.tokens, args.d_model)).astype(np.float32)
            experts = moe.route_tokens(args.tokens, world, args.skew, rng)
            t0 = time.perf_counter()
            expert_toks, _counts = disp.dispatch(toks, experts)
            disp.combine(expert_toks * 2.0)  # a stand-in expert
            lat_us.append((time.perf_counter() - t0) * 1e6)
            steps += 1
        # Bulk must run long enough to move its budget even if dispatch
        # finished early.
        while bulk_iters[0] < args.bulk_min_iters:
            time.sleep(0.01)
        stop.set()
        bt.join(timeout=120)
        m = telemetry.metrics()
        a2a = {}
        for key, v in m.get("tpunet_a2a_bytes_total", {}).items():
            lab = telemetry.labels(key)
            a2a[f"{lab['stage']}.{lab['dir']}"] = int(v)
        by_class = {}
        for key, v in m.get("tpunet_qos_bytes_total", {}).items():
            lab = telemetry.labels(key)
            by_class[f"{lab['class']}.{lab['dir']}"] = int(v)
        lat_us.sort()
        q.put((rank, {
            "ok": True,
            "steps": steps,
            "bulk_iters": bulk_iters[0],
            "p99_queue_wait_us": _p99_queue_wait_us(m, "latency"),
            "bulk_gated": _p99_queue_wait_us(m, "bulk") is not None,
            "a2a_bytes": a2a,
            "qos_bytes": by_class,
            "dispatch_p50_us": lat_us[len(lat_us) // 2] if lat_us else None,
            "dispatch_p99_us": lat_us[min(len(lat_us) - 1, int(0.99 * len(lat_us)))]
            if lat_us else None,
            "drop_fraction": disp.drop_fraction,
        }))
        lat.close()
        blk.close()
    except Exception as e:  # noqa: BLE001
        import traceback

        q.put((rank, {"ok": False,
                      "error": f"{type(e).__name__}: {e}",
                      "trace": traceback.format_exc()}))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--world", type=int, default=4)
    ap.add_argument("--tokens", type=int, default=256, help="tokens per rank per step")
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--capacity", type=int, default=192)
    ap.add_argument("--skew", type=float, default=float(os.environ.get("TPUNET_MOE_SKEW", "1.0")))
    ap.add_argument("--wire-dtype", default="f32", choices=["f32", "bf16", "int8"])
    ap.add_argument("--a2a", default="", choices=["", "auto", "pairwise", "ring", "hier"])
    ap.add_argument("--fake-hosts", type=int, default=1,
                    help=">1 splits the ranks into TPUNET_HOST_ID fake hosts (SHM intra)")
    ap.add_argument("--wire", default="256K", help="QoS wire window (TPUNET_QOS_INFLIGHT_BYTES)")
    ap.add_argument("--bulk-bytes", type=int, default=4 << 20)
    ap.add_argument("--bulk-min-iters", type=int, default=4)
    ap.add_argument("--steps", type=int, default=32,
                    help="dispatch/combine rounds (identical on every rank "
                         "— the exchanges are collectives)")
    ap.add_argument("--p99-budget-us", type=int, default=100_000)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--check", action="store_true")
    args = ap.parse_args()
    if args.fake_hosts > 1 and args.world % args.fake_hosts:
        ap.error("--world must divide evenly into --fake-hosts")

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tests"))
    from conftest import free_port

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    ports = (free_port(), free_port())
    procs = [ctx.Process(target=_rank_main, args=(r, args.world, ports, q, args))
             for r in range(args.world)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(args.world):
            rank, res = q.get(timeout=600)
            results[rank] = res
    finally:
        for p in procs:
            p.join(timeout=60)
            if p.is_alive():
                p.kill()
    failed = {r: v for r, v in results.items() if not v.get("ok")}
    if failed:
        print(json.dumps(failed, indent=2))
        return 1
    report = {
        "world": args.world,
        "skew": args.skew,
        "wire_dtype": args.wire_dtype,
        "per_rank": results,
    }
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True))
    else:
        for r in sorted(results):
            v = results[r]
            print(f"rank {r}: {v['steps']} dispatch steps, bulk x{v['bulk_iters']}, "
                  f"latency p99 queue wait {v['p99_queue_wait_us']}us, "
                  f"dispatch p99 {v['dispatch_p99_us']:.0f}us, "
                  f"drop {v['drop_fraction']:.3f}, a2a {v['a2a_bytes']}")
    if args.check:
        for r, v in results.items():
            assert v["p99_queue_wait_us"] is not None, \
                f"rank {r}: latency class never queued — gate unarmed?"
            assert v["p99_queue_wait_us"] <= args.p99_budget_us, \
                f"rank {r}: latency p99 queue wait {v['p99_queue_wait_us']}us"
            # Budget proof: the bulk tenant COMPLETED its AllReduce quota
            # (each iteration moves its full ring/hier byte share by
            # construction) and its class moved wire bytes. The exact
            # flat-ring byte formula only holds without a fake-host split
            # (under TPUNET_SHM the intra-host share rides the separate
            # tpunet_shm_bytes_total family) — apply it when it applies.
            assert v["bulk_iters"] >= args.bulk_min_iters, \
                f"rank {r}: bulk tenant starved: {v['bulk_iters']} iters"
            assert v["qos_bytes"].get("bulk.tx", 0) > 0, \
                f"rank {r}: bulk class moved no wire bytes: {v['qos_bytes']}"
            if args.fake_hosts <= 1:
                assert v["qos_bytes"]["bulk.tx"] >= \
                    args.bulk_min_iters * args.bulk_bytes * 2 * (args.world - 1) // args.world, \
                    f"rank {r}: bulk tenant starved: {v['qos_bytes']}"
        print("moe_bench check OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
