"""Two-tenant QoS bench: bulk AllReduce flood vs latency-class P2P pings.

The Transport-QoS acceptance bench (docs/DESIGN.md "Transport QoS"): two
spawned ranks run a bulk-class 64 MiB AllReduce loop; rank 0 concurrently
fires latency-class 64 KiB P2P pings at rank 1 (round-trip). Three phases,
all counter-gated (the PR 3/5 epistemic stance — wall-clock ratios are
reported for real-NIC runs, but the CLAIMS ride counters):

  1. bulk alone          -> the no-contention baseline (bytes + seconds)
  2. pings alone         -> the uncontended latency RTT floor
  3. bulk + pings        -> the contended run

Reported per rank 0:
  * latency-class p99 wire-credit queue wait (tpunet_qos_queue_wait_us)
    under contention — the scheduler-side bound;
  * ping RTT p50/p99 uncontended vs contended — the end-to-end view;
  * bulk bytes by counters in phases 1 and 3 (must be EQUAL: the gate
    reorders, it never drops) and the wall-clock ratio (the "within 10%"
    throughput claim on hardware where the wire, not the 1-core loopback
    memcpy floor, is the bottleneck);
  * per-class byte counters + preemptions.

`--check` asserts the gates (qos_smoke.py is the CI twin of this bench):
latency p99 queue wait <= --p99-budget-us AND contended bulk bytes match
the baseline.

Run:
  TPUNET_QOS_INFLIGHT_BYTES=wire=4M python -m benchmarks.qos_bench --json
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _percentile(xs, q):
    if not xs:
        return None
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def _p99_queue_wait_us(metrics, cls):
    from tpunet import telemetry

    buckets = []
    for key, value in metrics.get("tpunet_qos_queue_wait_us_bucket", {}).items():
        lab = telemetry.labels(key)
        if lab.get("class") != cls:
            continue
        le = lab["le"]
        buckets.append((float("inf") if le == "+Inf" else float(le), int(value)))
    buckets.sort()
    if not buckets or buckets[-1][1] == 0:
        return None
    total = buckets[-1][1]
    for bound, cum in buckets:
        if cum >= 0.99 * total:
            return bound
    return float("inf")


def _rank_main(rank, port, handle_q, out_q, args):
    try:
        import numpy as np

        from tpunet import telemetry
        from tpunet import transport as tp
        from tpunet.collectives import Communicator

        bulk_comm = Communicator(f"127.0.0.1:{port}", rank, 2,
                                 traffic_class="bulk")
        net_lat = tp.Net(traffic_class="latency")
        if rank == 1:
            lc = net_lat.listen()
            handle_q.put(bytes(lc.handle))
            rc = lc.accept()
            sc = net_lat.connect(handle_q.get(timeout=60))
        else:
            sc = net_lat.connect(handle_q.get(timeout=60))
            lc = net_lat.listen()
            handle_q.put(bytes(lc.handle))
            rc = lc.accept()

        grad = np.ones(args.bulk_bytes // 4, np.float32)
        ping = np.full(args.ping_bytes, 7, np.uint8)
        pong = np.empty_like(ping)

        def bulk_loop(n):
            t0 = time.monotonic()
            for _ in range(n):
                bulk_comm.all_reduce(grad)
            return time.monotonic() - t0

        stop = threading.Event()

        def ponger():
            # rank 1 echoes every ping back on the latency link.
            while not stop.is_set():
                try:
                    rc.irecv(pong).wait(timeout=1)
                except Exception:  # noqa: BLE001 — timeout poll
                    continue
                sc.isend(pong).wait(timeout=60)

        def ping_round():
            t0 = time.monotonic()
            sc.isend(ping).wait(timeout=60)
            rc.irecv(pong).wait(timeout=60)
            return (time.monotonic() - t0) * 1e3

        result = {"rank": rank}
        if rank == 1:
            th = threading.Thread(target=ponger, daemon=True)
            th.start()
            for phase in ("baseline", "contended"):
                result[f"bulk_{phase}_s"] = bulk_loop(args.iters)
            stop.set()
            th.join(timeout=5)
        else:
            telemetry.reset()
            result["bulk_baseline_s"] = bulk_loop(args.iters)
            m = telemetry.metrics()
            result["bulk_baseline_bytes"] = _qos_tx(m, "bulk")
            result["ping_rtt_ms_uncontended"] = [
                ping_round() for _ in range(args.pings)]
            telemetry.reset()
            rtts = []
            bulk_done = {}

            def bulk_bg():
                bulk_done["s"] = bulk_loop(args.iters)

            th = threading.Thread(target=bulk_bg, daemon=True)
            th.start()
            while th.is_alive():
                rtts.append(ping_round())
                time.sleep(args.ping_interval_ms / 1e3)
            th.join()
            m = telemetry.metrics()
            result.update(
                bulk_contended_s=bulk_done["s"],
                bulk_contended_bytes=_qos_tx(m, "bulk"),
                ping_rtt_ms_contended=rtts,
                lat_p99_queue_wait_us=_p99_queue_wait_us(m, "latency"),
                qos_bytes={
                    f"{telemetry.labels(k)['class']}/{telemetry.labels(k)['dir']}":
                        int(v)
                    for k, v in m.get("tpunet_qos_bytes_total", {}).items()},
                qos_preempts={
                    telemetry.labels(k)["class"]: int(v)
                    for k, v in m.get("tpunet_qos_preempts_total", {}).items()},
                wire_window=tp.qos_state()["wire_window"],
            )
        out_q.put((rank, "OK", result))
    except Exception as e:  # noqa: BLE001
        out_q.put((rank, f"FAIL: {type(e).__name__}: {e}", None))


def _qos_tx(metrics, cls):
    from tpunet import telemetry

    for k, v in metrics.get("tpunet_qos_bytes_total", {}).items():
        lab = telemetry.labels(k)
        if lab.get("class") == cls and lab.get("dir") == "tx":
            return int(v)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--iters", type=int, default=4,
                    help="bulk AllReduce iterations per phase")
    ap.add_argument("--bulk-bytes", type=int, default=64 << 20,
                    help="bulk AllReduce payload bytes (default 64MiB)")
    ap.add_argument("--ping-bytes", type=int, default=64 << 10,
                    help="latency-class ping bytes (default 64KiB)")
    ap.add_argument("--pings", type=int, default=32,
                    help="uncontended RTT samples")
    ap.add_argument("--ping-interval-ms", type=float, default=5.0)
    ap.add_argument("--p99-budget-us", type=float, default=100_000.0)
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--check", action="store_true",
                    help="assert the QoS gates (else report only)")
    args = ap.parse_args()

    # The gate must be armed before any native load; keep the operator's
    # setting when present, else a bench-sized default.
    os.environ.setdefault("TPUNET_QOS_INFLIGHT_BYTES", "wire=4M")
    os.environ.setdefault("TPUNET_QOS_WEIGHTS", "latency=8,bulk=1")

    ctx = mp.get_context("spawn")
    handle_q, out_q = ctx.Queue(), ctx.Queue()
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    procs = [ctx.Process(target=_rank_main, args=(r, port, handle_q, out_q, args))
             for r in range(2)]
    for p in procs:
        p.start()
    results = {}
    try:
        for _ in range(2):
            rank, status, payload = out_q.get(timeout=600)
            if status != "OK":
                raise RuntimeError(f"rank {rank}: {status}")
            results[rank] = payload
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.kill()

    r0 = results[0]
    summary = {
        "config": {"iters": args.iters, "bulk_bytes": args.bulk_bytes,
                   "ping_bytes": args.ping_bytes,
                   "qos_inflight_bytes": os.environ["TPUNET_QOS_INFLIGHT_BYTES"],
                   "qos_weights": os.environ["TPUNET_QOS_WEIGHTS"]},
        "bulk_baseline_s": r0["bulk_baseline_s"],
        "bulk_contended_s": r0["bulk_contended_s"],
        "bulk_slowdown": r0["bulk_contended_s"] / max(r0["bulk_baseline_s"], 1e-9),
        "bulk_baseline_bytes": r0["bulk_baseline_bytes"],
        "bulk_contended_bytes": r0["bulk_contended_bytes"],
        "lat_p99_queue_wait_us": r0["lat_p99_queue_wait_us"],
        "ping_rtt_ms": {
            "uncontended_p50": _percentile(r0["ping_rtt_ms_uncontended"], 0.5),
            "uncontended_p99": _percentile(r0["ping_rtt_ms_uncontended"], 0.99),
            "contended_p50": _percentile(r0["ping_rtt_ms_contended"], 0.5),
            "contended_p99": _percentile(r0["ping_rtt_ms_contended"], 0.99),
        },
        "qos_bytes": r0["qos_bytes"],
        "qos_preempts": r0["qos_preempts"],
        "wire_window": r0["wire_window"],
    }
    if args.json:
        print(json.dumps(summary, indent=2))
    else:
        print(f"bulk: {summary['bulk_baseline_s']:.2f}s solo -> "
              f"{summary['bulk_contended_s']:.2f}s contended "
              f"({summary['bulk_slowdown']:.2f}x)")
        print(f"latency p99 queue wait: {summary['lat_p99_queue_wait_us']}us; "
              f"ping p99 {summary['ping_rtt_ms']['uncontended_p99']:.2f} -> "
              f"{summary['ping_rtt_ms']['contended_p99']:.2f} ms")
    if args.check:
        p99 = summary["lat_p99_queue_wait_us"]
        assert p99 is not None and p99 <= args.p99_budget_us, p99
        # Budget parity by counters: both phases moved the full AllReduce
        # byte volume (ring wire bytes = payload per rank at W=2). Baseline
        # additionally carries a few wiring/quiesce token bytes, so compare
        # each phase against the payload floor, not phase-vs-phase.
        floor = args.iters * args.bulk_bytes
        assert summary["bulk_baseline_bytes"] >= floor
        assert summary["bulk_contended_bytes"] >= floor
        # The 10% throughput claim is a real-NIC number: on the 1-core
        # loopback box both tenants share one memcpy floor, so the check
        # there is generous (the counters above carry the strict claims).
        assert summary["bulk_slowdown"] <= 2.0, summary["bulk_slowdown"]
        print("qos bench checks OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
