"""Chip-sizing sweep: chained-timing MFU per transformer config on the
local accelerator. This is the tool that sized `tpu_headline`'s TPU config
(round-3 numbers recorded in PERF_NOTES.md): run it when the bench hardware
changes to re-pick the headline shape.

Usage: python -m benchmarks.mfu_sweep [config indices...]
Prints one JSON line per config: params, step time, tokens/s, TFLOP/s, MFU
(against the device's peak bf16 FLOP/s; null off-TPU or unknown kind).
"""

from __future__ import annotations

import json
import sys

CONFIGS = [
    # (d_model, layers, d_ff, heads, batch, seq, remat[, remat_policy])
    (2048, 12, 8192, 16, 8, 2048, True),   # the round-3 v5e headline winner
    (2048, 12, 8192, 16, 16, 2048, True),
    (2048, 16, 8192, 16, 8, 2048, True),   # OOM on 16 GB v5e
    (4096, 4, 16384, 32, 8, 2048, True),   # OOM on 16 GB v5e
    (1024, 12, 4096, 16, 16, 2048, True),  # half-size, for smaller chips
    # Long-context: flash O(S) memory is what makes s8192 fit at all —
    # reference attention would materialize b*h*S^2 scores (>8 GB here).
    (2048, 12, 8192, 16, 2, 8192, True),
    # Selective remat: full-block remat re-executes the forward (~8ND run vs
    # 6ND counted -> MFU ceiling 0.75); "dots" saves matmul outputs and
    # recomputes only elementwise, trading HBM back for recompute FLOPs.
    (2048, 12, 8192, 16, 8, 2048, True, "dots"),
    (2048, 12, 8192, 16, 8, 2048, False),  # no remat at all (OOM probe)
    (2048, 12, 8192, 16, 4, 2048, True, "dots"),  # dots at half batch
]

# Fused blockwise cross-entropy (tpunet.ops.blockwise_cross_entropy) per
# config index: skips materializing the (b*s, 32000) logits. Applied to the
# long-context config where that tensor is the limiting resident.
FUSED_XENT = {5: 8192}


def main(argv=None) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from benchmarks import chained_step_time
    from benchmarks.tpu_headline import _peak_for, transformer_flops_per_token
    from tpunet.models import Transformer
    from tpunet.train import create_train_state, make_train_step

    args = argv if argv is not None else sys.argv[1:]
    which = [int(x) for x in args] or list(range(len(CONFIGS)))
    dev = jax.devices()[0]
    peak = _peak_for(dev.device_kind) if dev.platform == "tpu" else None

    for ci in which:
        d, n_layers, ff, heads, batch, seq, remat, *rest = CONFIGS[ci]
        policy = rest[0] if rest else None
        cfg = dict(vocab=32000, d_model=d, n_layers=n_layers, n_heads=heads, d_ff=ff)
        model = Transformer(compute_dtype=jnp.bfloat16, attn_impl="flash",
                            remat=remat, remat_policy=policy, **cfg)
        tx = optax.adamw(3e-4)
        rng = np.random.default_rng(0)
        tokens = jnp.asarray(rng.integers(0, cfg["vocab"], (batch, seq)), jnp.int32)
        labels = jnp.roll(tokens, -1, axis=1)
        try:
            state, _ = create_train_state(model, jax.random.PRNGKey(0), tokens, tx)
            n_params = sum(x.size for x in jax.tree.leaves(state.params))
            step = make_train_step(model, tx,  # donated: real-training memory
                                   fused_xent_block=FUSED_XENT.get(ci))
            dt = chained_step_time(step, state,
                                   (tokens, labels, jax.random.PRNGKey(1)),
                                   warmup=1, iters=8)
        except Exception as e:  # noqa: BLE001 — a config OOMing is a result
            print(json.dumps({"cfg": ci, "error": str(e)[:200]}), flush=True)
            continue
        fpt = transformer_flops_per_token(n_params, cfg["vocab"], d, n_layers, seq)
        fps = fpt * batch * seq
        print(json.dumps({
            "cfg": ci, "d": d, "L": n_layers, "ff": ff, "b": batch, "s": seq,
            **({"remat_policy": policy} if policy else {}),
            **({} if remat else {"remat": False}),
            "params_M": round(n_params / 1e6, 1),
            "step_s": round(dt, 4),
            "tok_s": round(batch * seq / dt, 1),
            "tflops": round(fps / dt / 1e12, 1),
            "mfu": round(fps / dt / peak, 4) if peak else None,
        }), flush=True)


if __name__ == "__main__":
    main()
