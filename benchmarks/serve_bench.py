"""Continuous batching vs lockstep batching — serving throughput.

Workload: R requests, equal prompt length (so the lockstep baseline needs
no padding machinery), DIFFERENT generation lengths — the regime
continuous batching exists for. The lockstep baseline groups requests
into batches of `slots` and runs `generate()` per group with
max_new = the group's LONGEST request (every shorter request pays the
tail); the server retires each request at its own length and refills the
slot immediately.

Both paths produce each request's tokens with identical semantics (greedy
on the same weights), so the tokens/s ratio is pure scheduling: the
lockstep tail waste the server recovers. Lengths are drawn
deterministically (seeded) spanning short/long mix.

Prints ONE JSON line:
  {"platform", "slots", "requests", "serve_tok_s", "lockstep_tok_s",
   "vs_lockstep", ...}
"""

from __future__ import annotations

import argparse
import json
import time


def _iqr4(xs):
    from benchmarks import iqr

    spread = iqr(xs)
    return round(spread, 4) if spread is not None else None


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--platform", default="cpu", choices=["cpu", "tpu"])
    ap.add_argument("--d", type=int, default=64)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--ff", type=int, default=128)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--kv-heads", type=int, default=None,
                    help="GQA kv heads (default: MHA) - the serving cache "
                         "regime; shrinks the per-slot KV resident")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--requests", type=int, default=16)
    ap.add_argument("--prompt", type=int, default=32)
    ap.add_argument("--new-min", type=int, default=8)
    ap.add_argument("--new-max", type=int, default=64)
    ap.add_argument("--steps-per-call", type=int, default=8,
                    help="micro-steps scanned inside each jitted server "
                         "call - amortizes the host loop (generate()'s "
                         "lax.scan pays no such overhead at all). 8 won "
                         "the round-5 sweep {2,4,6,8,16,24,32,48} on the "
                         "CPU toy: small enough to keep the scheduling "
                         "win (retire/refill granularity), large enough "
                         "to amortize dispatch")
    ap.add_argument("--refill-coalesce", type=int, default=1,
                    help="hold freed slots until this many are free, then "
                         "refill them in one batched prefill. 1 (refill "
                         "immediately) measured best on this workload: "
                         "retirements are spread in time, so holding a "
                         "slot costs more idle windows than the batched "
                         "prefill saves")
    ap.add_argument("--pipeline", type=int, default=1,
                    help="in-flight decode windows (BatchServer.run): 1 "
                         "for single-core hosts (compute and host "
                         "serialize anyway), 2 on real accelerators so "
                         "host bookkeeping hides under device compute")
    ap.add_argument("--spec-gamma", type=int, default=None,
                    help="serve with speculative decoding: int8 SELF-draft "
                         "at this gamma (the BatchServer draft_model "
                         "path). The lockstep baseline stays plain "
                         "generate(), so vs_lockstep prices the whole "
                         "speculative pipeline; tok/round lands in the "
                         "JSON")
    ap.add_argument("--reps", type=int, default=7,
                    help="paired interleaved measurement passes "
                         "(serve/lockstep alternating); report medians + "
                         "IQR - single-shot walls on this box swing +-20%")
    args = ap.parse_args(argv)

    import jax

    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from tpunet.models import BatchServer, Transformer, generate

    model = Transformer(
        vocab=args.vocab, d_model=args.d, n_layers=args.layers,
        n_heads=args.heads, d_ff=args.ff, n_kv_heads=args.kv_heads,
        compute_dtype=jnp.bfloat16 if args.platform == "tpu"
        else jnp.float32)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, args.vocab, args.prompt).astype(np.int32)
               for _ in range(args.requests)]
    news = rng.integers(args.new_min, args.new_max + 1,
                        args.requests).tolist()
    max_len = args.prompt + args.new_max
    params = model.init(jax.random.PRNGKey(0),
                        jnp.asarray(prompts[0][None]))["params"]
    total_tokens = int(sum(news))

    # --- continuous batching ---
    # Warm THE SERVER'S OWN jits (they are per-instance closures: a
    # throwaway warm server would leave the timed one cold): one prefill
    # trace — all prompts share a length — plus the decode window.
    spec_kw = {}
    if args.spec_gamma is not None:
        from tpunet.models import quantize_params

        spec_kw = dict(draft_model=model.clone(weight_quant="int8"),
                       draft_params=quantize_params(params),
                       gamma=args.spec_gamma)
    srv = BatchServer(model, params, slots=args.slots, max_len=max_len,
                      steps_per_call=args.steps_per_call,
                      refill_coalesce=args.refill_coalesce, **spec_kw)
    srv.submit(prompts[0], 2)
    srv.run()
    # Warm EVERY batched refill trace (n, p) for n in 1..slots — the
    # startup fill is (slots, p) and same-window retirements produce the
    # intermediate sizes; without this they compile inside the timed
    # passes. State surgery through the private hook is deliberate: group
    # sizes are not controllable through the public API, and the junk it
    # prefills is reset by the first real refill anyway.
    for n in range(1, args.slots + 1):
        warm_prompts = jnp.tile(jnp.asarray(prompts[0][None]), (n, 1))
        warm_rows = jnp.asarray(np.arange(n, dtype=np.int32))
        if args.spec_gamma is not None:
            (srv._cache, srv._dcache, srv._toks, _,
             srv._key) = srv._spec_prefill_slots(
                srv._cache, srv._dcache, srv._toks, warm_prompts,
                warm_rows, srv._key, None)
        else:
            srv._cache, srv._toks, _, srv._key = srv._prefill_slots(
                srv._cache, srv._toks, warm_prompts, warm_rows, srv._key,
                None)

    def serve_pass():
        t0 = time.perf_counter()
        for p, n in zip(prompts, news):
            srv.submit(p, int(n))
        results = srv.run(pipeline=args.pipeline)
        dt = time.perf_counter() - t0
        assert len(results) == args.requests
        return dt

    # --- lockstep baseline: batches of `slots`, each runs to its group's
    # longest request ---
    gen = jax.jit(
        lambda params, prompt, n: generate(model, params, prompt, n),
        static_argnames=("n",))
    groups = [list(range(i, min(i + args.slots, args.requests)))
              for i in range(0, args.requests, args.slots)]
    # Warm one compile per distinct group max_new.
    for g in {max(news[i] for i in g) for g in groups}:
        np.asarray(gen(params, jnp.asarray(
            np.stack([prompts[0]] * args.slots)), int(g)))

    def lockstep_pass():
        t0 = time.perf_counter()
        for g in groups:
            batch = np.stack([prompts[i] for i in g]
                             + [prompts[g[0]]] * (args.slots - len(g)))
            n = max(news[i] for i in g)
            np.asarray(gen(params, jnp.asarray(batch), int(n)))
        return time.perf_counter() - t0

    # Interleaved A/B passes: box-noise drift (cpu freq, neighbors) hits
    # both sides equally; medians resist the stragglers.
    serve_walls, lockstep_walls = [], []
    windows0 = srv.stats["decode_windows"]
    for _ in range(max(args.reps, 1)):
        serve_walls.append(serve_pass())
        lockstep_walls.append(lockstep_pass())
    serve_micro = ((srv.stats["decode_windows"] - windows0)
                   * args.steps_per_call // max(args.reps, 1))
    serve_s = float(np.median(serve_walls))
    lockstep_s = float(np.median(lockstep_walls))

    print(json.dumps({
        "platform": jax.devices()[0].platform,
        "slots": args.slots, "requests": args.requests,
        "prompt": args.prompt, "new_min": args.new_min,
        "new_max": args.new_max, "steps_per_call": args.steps_per_call,
        "refill_coalesce": args.refill_coalesce,
        "pipeline": args.pipeline,
        **({"spec_gamma": args.spec_gamma,
            "spec_tok_per_round": round(
                srv.stats["spec_committed"]
                / max(srv.stats["spec_rounds"], 1), 3)}
           if args.spec_gamma is not None else {}),
        "useful_tokens": total_tokens,
        "reps": args.reps,
        "serve_wall_s": round(serve_s, 3),
        "lockstep_wall_s": round(lockstep_s, 3),
        "serve_iqr_s": _iqr4(serve_walls),
        "lockstep_iqr_s": _iqr4(lockstep_walls),
        "serve_tok_s": round(total_tokens / serve_s, 1),
        "lockstep_tok_s": round(total_tokens / lockstep_s, 1),
        "vs_lockstep": round(lockstep_s / serve_s, 3),
        # The dispatch-independent scheduling quantity: batch micro-steps
        # each path runs. At real model scale (step cost >> dispatch) the
        # wall-clock ratio converges to this one; on a toy CPU model the
        # wall ratio is dominated by the server's per-window host loop,
        # which generate()'s in-jit lax.scan never pays.
        "serve_micro_steps": serve_micro,
        "lockstep_micro_steps": int(sum(max(news[i] for i in g)
                                        for g in groups)),
        "sched_win": round(sum(max(news[i] for i in g) for g in groups)
                           / max(serve_micro, 1), 3),
    }))


if __name__ == "__main__":
    main()
