"""Autoregressive decode throughput: tokens/s out of `tpunet.models.generate`.

The training headline (`tpu_headline`) measures MXU-bound step throughput;
this measures the inference regime the KV cache exists for — one token per
step, attention against the cached prefix, batch as the only MXU feeder.
GQA directly scales this bench: the KV cache (the HBM resident that limits
batch) shrinks by n_heads/n_kv_heads.

The whole generate() call — prefill + lax.scan decode — is wrapped in ONE
jit, so the timed region is a single executable; syncing happens by
transferring the token matrix to host (correct on the axon tunnel, where
block_until_ready does not sync — PERF_NOTES.md).

Usage: python -m benchmarks.decode_bench [--platform cpu|tpu] [--kv-heads K]
Prints one JSON line: config, prefill+decode wall, decode tokens/s.
"""

from __future__ import annotations

import argparse
import json
import time


def main(argv=None) -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--platform", default="cpu", choices=["cpu", "tpu"])
    p.add_argument("--d", type=int, default=1024)
    p.add_argument("--layers", type=int, default=12)
    p.add_argument("--heads", type=int, default=16)
    p.add_argument("--ff", type=int, default=4096)
    p.add_argument("--vocab", type=int, default=32000)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--prompt", type=int, default=128)
    p.add_argument("--new", type=int, default=128)
    p.add_argument("--kv-heads", type=int, default=None,
                   help="grouped-query kv heads (default: = heads)")
    p.add_argument("--window", type=int, default=None,
                   help="sliding-window attention span (default: full causal)")
    p.add_argument("--iters", type=int, default=3)
    p.add_argument("--spec-gamma", type=int, default=None,
                   help="also bench speculative decoding with this draft "
                        "block length")
    p.add_argument("--draft-layers", type=int, default=2,
                   help="draft model depth for --spec-gamma shallow mode "
                        "(same d/heads/vocab; random weights)")
    p.add_argument("--spec-per-row", action="store_true",
                   help="per-row speculative commits (each row keeps its "
                        "own accepted prefix; lockstep min otherwise)")
    p.add_argument("--spec-draft", choices=["shallow", "quant"],
                   default="shallow",
                   help="shallow = random small draft (acceptance floor + "
                        "analytic ceiling); quant = the target itself, "
                        "int8-quantized (a REAL draft: high acceptance, "
                        "honest end-to-end tokens/s)")
    p.add_argument("--quant", choices=["int8"], default=None,
                   help="also bench the int8 weight-only model's decode "
                        "tokens/s (halved weight HBM traffic)")
    p.add_argument("--attn", choices=["reference", "flash"],
                   default="reference",
                   help="attention impl: decode steps always use the cached "
                        "dense path, but the EMPTY-CACHE prefill routes "
                        "through this kernel — flash makes time-to-first-"
                        "token O(p) memory and MXU-tiled (chip_session "
                        "gates it on the kernel smoke, like the headline)")
    args = p.parse_args(argv)

    import jax

    if args.platform == "cpu":
        # The axon sitecustomize pins jax_platforms at interpreter start;
        # env alone cannot override it (verify skill, session-2 notes).
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from tpunet.models import Transformer, generate

    model = Transformer(
        vocab=args.vocab, d_model=args.d, n_layers=args.layers,
        n_heads=args.heads, d_ff=args.ff, n_kv_heads=args.kv_heads,
        attn_window=args.window, compute_dtype=jnp.bfloat16,
        attn_impl=args.attn,
    )
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(0, args.vocab, (args.batch, args.prompt)), jnp.int32
    )
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]

    gen = jax.jit(
        lambda params, prompt: generate(model, params, prompt, args.new)
    )
    out = np.asarray(gen(params, prompt))  # compile + warm
    assert out.shape == (args.batch, args.prompt + args.new)

    times = []
    for _ in range(args.iters):
        t0 = time.perf_counter()
        np.asarray(gen(params, prompt))  # host transfer = the sync point
        times.append(time.perf_counter() - t0)
    best = min(times)
    n_params = sum(x.size for x in jax.tree.leaves(params))

    quant = None
    if args.quant is not None:
        # Same weights, int8 kernels: decode is weight-HBM-bound, so the
        # tokens/s delta IS the bandwidth story (quality tracked separately
        # by tests/test_quant.py's closeness bounds).
        from tpunet.models import quantize_params

        qmodel = model.clone(weight_quant="int8")
        qparams = quantize_params(params)
        qgen = jax.jit(
            lambda qp, prompt: generate(qmodel, qp, prompt, args.new))
        np.asarray(qgen(qparams, prompt))  # compile + warm
        qtimes = []
        for _ in range(args.iters):
            t0 = time.perf_counter()
            np.asarray(qgen(qparams, prompt))
            qtimes.append(time.perf_counter() - t0)
        qbest = min(qtimes)
        quant = {
            "dtype": "int8",
            "wall_s": round(qbest, 4),
            "decode_tok_s": round(args.batch * args.new / qbest, 1),
            "vs_fp": round(best / qbest, 3),
        }

    spec = None
    if args.spec_gamma is not None:
        # An UNTRAINED draft can't agree with an untrained target, so the
        # measured tokens/s here is the acceptance FLOOR. But a round is
        # the same static program whatever gets accepted — acceptance only
        # changes how many rounds run — so the same run also yields the
        # round cost, and with it the perfect-draft CEILING
        # (gamma+1 committed tokens per round). A real (distilled/trained)
        # draft lands between floor and ceiling by its acceptance rate;
        # both bounds are measured hardware numbers, not projections.
        from tpunet.models import speculative_generate

        if args.spec_draft == "quant":
            # The realistic cheap draft: the target itself at int8. Near-fp
            # agreement makes acceptance high, so the measured tokens/s is
            # an honest end-to-end speculative number, not a bound. Reuse
            # the --quant tier's tree when it exists — a second int8 copy
            # would double-count HBM on the bench accounting for it.
            if quant is not None:
                draft, draft_params = qmodel, qparams
            else:
                from tpunet.models import quantize_params

                draft = model.clone(weight_quant="int8")
                draft_params = quantize_params(params)
        else:
            draft = model.clone(n_layers=args.draft_layers)
            draft_params = draft.init(jax.random.PRNGKey(1), prompt)["params"]
        sgen = jax.jit(
            lambda params, dparams, prompt: speculative_generate(
                model, params, draft, dparams, prompt, args.new,
                gamma=args.spec_gamma, per_row=args.spec_per_row,
                return_stats=True))
        out, stats = sgen(params, draft_params, prompt)  # compile + warm
        np.asarray(out)
        stimes = []
        for _ in range(args.iters):
            t0 = time.perf_counter()
            out, stats = sgen(params, draft_params, prompt)
            np.asarray(out)  # host transfer = the sync point
            stimes.append(time.perf_counter() - t0)
        sbest = min(stimes)
        rounds = int(stats["rounds"])
        round_s = sbest / rounds
        spec = {
            "gamma": args.spec_gamma,
            "draft": args.spec_draft,
            "per_row": args.spec_per_row,
            **({"draft_layers": args.draft_layers}
               if args.spec_draft == "shallow" else {}),
            "wall_s": round(sbest, 4),
            "rounds": rounds,
            # Shallow-random drafts can't agree with the target, so their
            # measured rate/tokens are the acceptance FLOOR; the quant
            # draft is a real draft and its numbers are plain measurements.
            **({"accept_rate_floor": round(
                    float(stats["draft_accept_rate"]), 4),
                "spec_tok_s_floor": round(args.batch * args.new / sbest, 1)}
               if args.spec_draft == "shallow" else
               {"accept_rate": round(float(stats["draft_accept_rate"]), 4),
                "spec_tok_s": round(args.batch * args.new / sbest, 1),
                "vs_plain": round(best / sbest, 3)}),
            "round_s": round(round_s, 5),
            "spec_tok_s_ceiling": round(
                args.batch * (args.spec_gamma + 1) / round_s, 1),
        }

    print(json.dumps({
        "platform": jax.devices()[0].platform,
        "attn": args.attn,
        "d": args.d, "L": args.layers, "heads": args.heads,
        "kv_heads": args.kv_heads or args.heads,
        "window": args.window,
        "params_M": round(n_params / 1e6, 1),
        "batch": args.batch, "prompt": args.prompt, "new": args.new,
        "wall_s": round(best, 4),
        "decode_tok_s": round(args.batch * args.new / best, 1),
        **({"quant": quant} if quant is not None else {}),
        **({"speculative": spec} if spec is not None else {}),
    }))


if __name__ == "__main__":
    main()
