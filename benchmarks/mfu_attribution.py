"""Per-segment MFU attribution for the headline train step.

The round-3 headline (MFU 0.411 on v5e) left ~59% of the chip unexplained
— nothing in the repo could say where a step's time goes. This tool times
each segment of the headline step IN ISOLATION with the same chained-
timing methodology the headline uses (sync once at the end of a K-step
dependency chain — per-step sync is wrong on the tunneled platform,
benchmarks.__init__), then reconciles the sum against the measured full
step:

  expected_full = L*(attn + qkvo + ffn)[fwd+bwd]           (the blocks)
                + L*(attn + qkvo + ffn)[fwd]               (remat recompute)
                + xent[fwd+bwd] + adamw                    (head + optimizer)
  residual      = measured_full - expected_full            (LN, elementwise,
                                                            embed, dispatch)

Each segment also gets an analytic FLOP count (same 6N/12LSd convention as
benchmarks.tpu_headline, so shares line up with the headline MFU) and a
per-segment efficiency = FLOPs / time / peak — the column that says which
segment to tune. Segment chaining perturbs inputs by the carry scalar and
consumes grads with a tree-sum; both add O(bytes) elementwise work
(~5-10% overhead at headline shapes), so treat per-segment efficiencies as
slightly pessimistic, and the residual as slightly optimistic.

--sweep-blocks instead times the attention segment alone over a grid of
flash (block_q, block_k) at the given seq — the tool for picking kernel
block sizes at s2048 vs s8192 (verdict round 3 item 4).

Prints ONE JSON line.
"""

from __future__ import annotations

import argparse
import json
import math
import time


def _chained_time(fn, carry0, warmup: int, iters: int) -> float:
    """Per-call seconds for carry -> carry scalar chains, synced once."""
    carry = carry0
    for _ in range(max(warmup, 1)):
        carry = fn(carry)
    if not math.isfinite(float(carry)):
        raise RuntimeError("non-finite carry in warmup")
    t0 = time.perf_counter()
    for _ in range(iters):
        carry = fn(carry)
    final = float(carry)  # the one chain-wide sync the platform honors
    dt = (time.perf_counter() - t0) / iters
    if not math.isfinite(final):
        raise RuntimeError("non-finite carry in timing chain")
    return dt


def _tree_sum(tree):
    import jax
    import jax.numpy as jnp

    return sum(jnp.sum(x.astype(jnp.float32)) for x in jax.tree.leaves(tree))


def segments(cfg: dict, *, block_q: int = 128, block_k: int = 128):
    """Build {name: (chained_fn, carry0, flops_fwd, flops_fwdbwd)} for one
    layer's blocks plus the model-level head/optimizer segments.

    FLOP convention matches tpu_headline.transformer_flops_per_token: 2*m*n*k
    per matmul forward, bwd = 2x fwd, attention 4*B*S^2*d fwd (no causal
    discount). adamw gets flops=0 — it is HBM-bound; its line is time-only.
    """
    import jax
    import jax.numpy as jnp
    import optax

    from tpunet.ops.flash_attention import flash_attention

    B, S, d, ff, H, V = (cfg["batch"], cfg["seq"], cfg["d_model"],
                         cfg["d_ff"], cfg["n_heads"], cfg["vocab"])
    dh = d // H
    key = jax.random.PRNGKey(0)
    dtype = jnp.bfloat16 if cfg["bf16"] else jnp.float32
    x0 = jax.random.normal(key, (B * S, d), dtype)
    qkv0 = jax.random.normal(key, (B, S, H, dh), dtype)
    out: dict = {}

    use_flash = cfg["bf16"]  # flash needs tile shapes; CPU smoke uses ref

    def attn_fwd(c):
        q = qkv0 * (1 + c * 1e-6)
        if use_flash:
            o = flash_attention(q, q, q, True, block_q=block_q,
                                block_k=block_k)
        else:
            from tpunet.ops.flash_attention import attention_reference

            o = attention_reference(q, q, q, True)
        return jnp.sum(o.astype(jnp.float32)) * 1e-9

    def attn_fwdbwd(c):
        def loss(q):
            if use_flash:
                o = flash_attention(q, q, q, True, block_q=block_q,
                                    block_k=block_k)
            else:
                from tpunet.ops.flash_attention import attention_reference

                o = attention_reference(q, q, q, True)
            return jnp.sum(o.astype(jnp.float32))

        v, g = jax.value_and_grad(loss)(qkv0 * (1 + c * 1e-6))
        return (v + _tree_sum(g)) * 1e-9

    a_fwd = 4 * B * S * S * d  # QK^T + PV, 2*B*H*S*S*dh each
    out["attn"] = (attn_fwd, attn_fwdbwd, a_fwd, 3 * a_fwd)

    w_qkvo = [jax.random.normal(jax.random.PRNGKey(i + 1), (d, d), dtype) * 0.02
              for i in range(4)]

    def qkvo_fwd(c):
        x = x0 * (1 + c * 1e-6)
        acc = 0.0
        for w in w_qkvo:
            acc = acc + jnp.sum((x @ w).astype(jnp.float32))
        return acc * 1e-9

    def qkvo_fwdbwd(c):
        def loss(x, ws):
            return sum(jnp.sum((x @ w).astype(jnp.float32)) for w in ws)

        v, g = jax.value_and_grad(loss, argnums=(0, 1))(x0 * (1 + c * 1e-6),
                                                        w_qkvo)
        return (v + _tree_sum(g)) * 1e-9

    p_fwd = 2 * B * S * 4 * d * d
    out["qkvo"] = (qkvo_fwd, qkvo_fwdbwd, p_fwd, 3 * p_fwd)

    w_up = jax.random.normal(jax.random.PRNGKey(11), (d, ff), dtype) * 0.02
    w_dn = jax.random.normal(jax.random.PRNGKey(12), (ff, d), dtype) * 0.02

    def ffn_fwd(c):
        x = x0 * (1 + c * 1e-6)
        return jnp.sum((jax.nn.gelu(x @ w_up) @ w_dn).astype(jnp.float32)) * 1e-9

    def ffn_fwdbwd(c):
        def loss(x, wu, wd):
            return jnp.sum((jax.nn.gelu(x @ wu) @ wd).astype(jnp.float32))

        v, g = jax.value_and_grad(loss, argnums=(0, 1, 2))(
            x0 * (1 + c * 1e-6), w_up, w_dn)
        return (v + _tree_sum(g)) * 1e-9

    f_fwd = 2 * B * S * 2 * d * ff
    out["ffn"] = (ffn_fwd, ffn_fwdbwd, f_fwd, 3 * f_fwd)

    w_head = jax.random.normal(jax.random.PRNGKey(13), (d, V), dtype) * 0.02
    labels0 = jax.random.randint(jax.random.PRNGKey(14), (B * S,), 0, V)

    def xent_fwdbwd(c):
        def loss(x, w):
            logits = (x @ w).astype(jnp.float32)
            return jnp.mean(optax.softmax_cross_entropy_with_integer_labels(
                logits, labels0))

        v, g = jax.value_and_grad(loss, argnums=(0, 1))(x0 * (1 + c * 1e-6),
                                                        w_head)
        return v + _tree_sum(g) * 1e-9

    x_fwd = 2 * B * S * d * V
    out["xent"] = (None, xent_fwdbwd, x_fwd, 3 * x_fwd)
    return out


def _adamw_segment(n_params_target: int, warmup: int, iters: int) -> float:
    """Time an adamw update on a f32 param tree of ~n_params_target,
    chained through (params, opt_state). HBM-bound: p+m+v+g traffic."""
    import jax
    import jax.numpy as jnp
    import optax

    # A few big leaves, like a real model (per-leaf overhead is negligible
    # either way at headline scale).
    n_leaf = max(n_params_target // 8, 1)
    params = [jax.random.normal(jax.random.PRNGKey(i), (n_leaf,), jnp.float32)
              for i in range(8)]
    grads = [jnp.full((n_leaf,), 1e-4, jnp.float32) for _ in range(8)]
    tx = optax.adamw(3e-4)
    opt_state = tx.init(params)

    @jax.jit
    def upd(params, opt_state):
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    def fn(carry):
        p, s = carry
        return upd(p, s)

    carry = (params, opt_state)
    for _ in range(max(warmup, 1)):
        carry = fn(carry)
    float(jnp.sum(carry[0][0][:1]))  # sync warmup
    t0 = time.perf_counter()
    for _ in range(iters):
        carry = fn(carry)
    float(jnp.sum(carry[0][0][:1]))  # chain-wide sync (depends on all steps)
    return (time.perf_counter() - t0) / iters


def run_attribution(cfg: dict, warmup: int, iters: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from benchmarks import chained_step_time
    from benchmarks.tpu_headline import _peak_for, transformer_flops_per_token
    from tpunet.models import Transformer
    from tpunet.train import create_train_state, make_train_step

    dev = jax.devices()[0]
    peak = _peak_for(dev.device_kind) if dev.platform == "tpu" else None
    L = cfg["n_layers"]

    segs = segments(cfg)
    rows: dict[str, dict] = {}
    for name, (fwd, fwdbwd, fl_fwd, fl_fwdbwd) in segs.items():
        jitted_b = jax.jit(fwdbwd)
        t_b = _chained_time(jitted_b, jnp.float32(0), warmup, iters)
        row = {"fwdbwd_ms": round(t_b * 1e3, 3),
               "eff_fwdbwd": round(fl_fwdbwd / t_b / peak, 3) if peak else None}
        if fwd is not None:
            t_f = _chained_time(jax.jit(fwd), jnp.float32(0), warmup, iters)
            row["fwd_ms"] = round(t_f * 1e3, 3)
            row["eff_fwd"] = round(fl_fwd / t_f / peak, 3) if peak else None
        rows[name] = row

    # Optimizer on the real parameter count.
    model = Transformer(
        vocab=cfg["vocab"], d_model=cfg["d_model"], n_layers=L,
        n_heads=cfg["n_heads"], d_ff=cfg["d_ff"],
        compute_dtype=jnp.bfloat16 if cfg["bf16"] else jnp.float32,
        attn_impl="flash" if cfg["bf16"] else "reference", remat=cfg["bf16"])
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg["vocab"],
                                      (cfg["batch"], cfg["seq"])), jnp.int32)
    labels = jnp.roll(tokens, -1, axis=1)
    tx = optax.adamw(3e-4)
    state, _ = create_train_state(model, jax.random.PRNGKey(0), tokens, tx)
    n_params = sum(x.size for x in jax.tree.leaves(state.params))
    rows["adamw"] = {"fwdbwd_ms": round(
        _adamw_segment(n_params, warmup, iters) * 1e3, 3)}

    # The measured full step, same harness as the headline.
    step = make_train_step(model, tx)
    t_full = chained_step_time(
        step, state, (tokens, labels, jax.random.PRNGKey(1)),
        warmup=warmup, iters=iters)

    blocks_fwdbwd = sum(rows[n]["fwdbwd_ms"] for n in ("attn", "qkvo", "ffn"))
    blocks_fwd = sum(rows[n]["fwd_ms"] for n in ("attn", "qkvo", "ffn"))
    expected = (L * (blocks_fwdbwd + (blocks_fwd if cfg["bf16"] else 0))
                + rows["xent"]["fwdbwd_ms"] + rows["adamw"]["fwdbwd_ms"])
    flops_step = transformer_flops_per_token(
        n_params, cfg["vocab"], cfg["d_model"], L, cfg["seq"]
    ) * cfg["batch"] * cfg["seq"]
    return {
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "config": {k: cfg[k] for k in ("d_model", "n_layers", "d_ff",
                                       "n_heads", "batch", "seq")},
        "n_params": n_params,
        "segments": rows,
        "full_step_ms": round(t_full * 1e3, 3),
        "mfu": round(flops_step / t_full / peak, 4) if peak else None,
        # remat=True re-runs each block's forward during bwd; the expected
        # model includes that extra fwd pass per layer.
        "expected_full_ms": round(expected, 3),
        "residual_ms": round(t_full * 1e3 - expected, 3),
        "note": "segments timed in isolation (chained, one sync); "
                "residual = LN + elementwise + embed + dispatch + "
                "model-vs-segment discrepancies",
    }


def run_block_sweep(cfg: dict, blocks: list[int], warmup: int,
                    iters: int) -> dict:
    import jax
    import jax.numpy as jnp

    from benchmarks.tpu_headline import _peak_for

    dev = jax.devices()[0]
    peak = _peak_for(dev.device_kind) if dev.platform == "tpu" else None
    a_fwdbwd = 12 * cfg["batch"] * cfg["seq"] * cfg["seq"] * cfg["d_model"]
    grid: dict[str, dict] = {}
    for bq in blocks:
        for bk in blocks:
            if bq > cfg["seq"] or bk > cfg["seq"]:
                continue
            # Untileable pairs silently fall back to the reference einsum
            # inside flash_attention, and compiled Mosaic silently clamps
            # non-lane-aligned blocks (_normalize_blocks) — timing either
            # would crown a fake "best". Both rules the model-level knob
            # enforces (transformer.py SelfAttention validation).
            if cfg["seq"] % bq or cfg["seq"] % bk or bq % bk:
                grid[f"bq{bq}_bk{bk}"] = {"skipped": "untileable (causal)"}
                continue
            min_sublane = 16 if cfg["bf16"] else 8
            if ((bq % 128 and bq != cfg["seq"])
                    or (bk % min_sublane and bk != cfg["seq"])):
                grid[f"bq{bq}_bk{bk}"] = {
                    "skipped": "not Mosaic-legal (would be clamped)"}
                continue
            segs = segments(cfg, block_q=bq, block_k=bk)
            _, fwdbwd, _, _ = segs["attn"]
            try:
                t = _chained_time(jax.jit(fwdbwd), jnp.float32(0),
                                  warmup, iters)
                grid[f"bq{bq}_bk{bk}"] = {
                    "fwdbwd_ms": round(t * 1e3, 3),
                    "eff": round(a_fwdbwd / t / peak, 3) if peak else None}
            except Exception as e:  # noqa: BLE001 — a Mosaic reject is data
                grid[f"bq{bq}_bk{bk}"] = {
                    "error": f"{type(e).__name__}: {str(e).splitlines()[0][:200]}"}
    ok = {k: v["fwdbwd_ms"] for k, v in grid.items() if "fwdbwd_ms" in v}
    return {
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "seq": cfg["seq"], "batch": cfg["batch"], "d_model": cfg["d_model"],
        "grid": grid,
        "best": min(ok, key=ok.get) if ok else None,
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--d", type=int, default=2048)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--ff", type=int, default=8192)
    ap.add_argument("--heads", type=int, default=16)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--fp32", action="store_true",
                    help="CPU smoke mode: f32 + reference attention")
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--iters", type=int, default=6)
    ap.add_argument("--sweep-blocks", action="store_true",
                    help="time the attention segment over a flash "
                         "(block_q, block_k) grid instead")
    ap.add_argument("--blocks", type=int, nargs="+",
                    default=[128, 256, 512])
    args = ap.parse_args(argv)

    cfg = dict(d_model=args.d, n_layers=args.layers, d_ff=args.ff,
               n_heads=args.heads, vocab=args.vocab, batch=args.batch,
               seq=args.seq, bf16=not args.fp32)
    if args.sweep_blocks:
        print(json.dumps(run_block_sweep(cfg, args.blocks, args.warmup,
                                         args.iters)))
    else:
        print(json.dumps(run_attribution(cfg, args.warmup, args.iters)))


if __name__ == "__main__":
    main()
