"""VGG16 synthetic data-parallel training benchmark (img/s).

In-repo replacement for the reference's end-to-end benchmark — Bagua's
`synthetic_benchmark.py` VGG16 run (reference README.md:52-84: 4046.6 ± 205.2
img/s total on 32 V100 with the multi-stream transport vs 2744.9 ± 122.3
baseline). Same shape: synthetic ImageNet-sized batches, timed iterations,
img/s mean ± std, per-device and total.

Modes:
  Single process (default): DP over the local `jax.devices()` mesh — the
  in-pod tier; XLA inserts the gradient all-reduce over ICI.
      python -m benchmarks.vgg_synthetic --iters 5
  Multi-process (-n N): N ranks on 127.0.0.1, each running the jitted local
  step plus the cross-host DCN gradient tier over the tpunet transport
  (`make_train_step(cross_host=True)`) — the configuration whose scaling the
  reference's numbers measure. Total img/s sums ranks.
      python -m benchmarks.vgg_synthetic -n 2 --width-mult 0.125
"""

from __future__ import annotations

import argparse
import math
import os
import statistics
import sys
import time


def _build(args):
    import jax
    import jax.numpy as jnp
    import optax

    from tpunet.models import VGG, VGG16_CFG
    from tpunet.train import create_train_state, make_train_step, synthetic_batch

    model = VGG(
        cfg=VGG16_CFG,
        num_classes=args.classes,
        width_mult=args.width_mult,
        hidden=max(8, int(4096 * args.width_mult)),
        compute_dtype=jnp.bfloat16 if args.bf16 else jnp.float32,
        classifier_dropout=0.0,
    )
    tx = optax.sgd(0.01, momentum=0.9)
    import numpy as np

    rng = np.random.default_rng(0)
    images, labels = synthetic_batch(rng, args.batch_size, args.image_size, args.classes)
    state, _ = create_train_state(
        model, jax.random.PRNGKey(0), jnp.asarray(images), tx
    )
    step = make_train_step(model, tx, cross_host=args.cross_host, donate=True)
    return state, step, jnp.asarray(images), jnp.asarray(labels)


def run_benchmark(args, emit=print):
    import jax

    state, step, images, labels = _build(args)
    rngkey = jax.random.PRNGKey(1)

    # Warmup (compile).
    loss = None
    for _ in range(args.warmup):
        state, loss = step(state, images, labels, rngkey)
    if loss is not None:
        loss.block_until_ready()

    rates = []
    for it in range(args.iters):
        t0 = time.perf_counter()
        for _ in range(args.batches_per_iter):
            state, loss = step(state, images, labels, rngkey)
        loss.block_until_ready()
        dt = time.perf_counter() - t0
        rates.append(args.batch_size * args.batches_per_iter / dt)
        emit(f"Iter #{it}: {rates[-1]:.1f} img/sec")
    if not math.isfinite(float(loss)):
        raise RuntimeError("non-finite loss during benchmark")
    return rates


def _mp_worker(rank, world, port, q, argv):
    try:
        from benchmarks import reassert_jax_platform

        reassert_jax_platform("cpu")  # loopback ranks cannot share one TPU
        args = _parse(argv)
        from tpunet import distributed

        distributed.initialize(f"127.0.0.1:{port}", rank, world)
        args.cross_host = True
        rates = run_benchmark(args, emit=lambda *_: None)
        distributed.finalize()
        q.put((rank, ("OK", rates)))
    except Exception as e:  # noqa: BLE001
        q.put((rank, (f"FAIL: {type(e).__name__}: {e}", [])))


def _parse(argv):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-n", "--world", type=int, default=1, help="ranks (multi-process DP)")
    ap.add_argument("--batch-size", type=int, default=32, help="per-process batch")
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--classes", type=int, default=1000)
    ap.add_argument("--width-mult", type=float, default=1.0)
    ap.add_argument("--bf16", action="store_true", default=True)
    ap.add_argument("--no-bf16", dest="bf16", action="store_false")
    ap.add_argument("--warmup", type=int, default=2)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument("--batches-per-iter", type=int, default=3)
    ap.add_argument("--cross-host", action="store_true",
                    help="add the DCN gradient tier (needs TPUNET_* env)")
    return ap.parse_args(argv)


def main(argv=None):
    args = _parse(argv)
    if args.world == 1:
        from benchmarks import reassert_jax_platform

        reassert_jax_platform()  # the world>1 parent never runs JAX
    if args.world > 1:
        from benchmarks import check_rank_results, spawn_ranks

        results = check_rank_results(spawn_ranks(
            _mp_worker, args.world, extra_args=(argv or sys.argv[1:],), timeout=3600
        ))
        per_rank = [results[r] for r in range(args.world)]
        totals = [sum(it) for it in zip(*per_rank)]
        mean, std = statistics.mean(totals), statistics.pstdev(totals)
        per = mean / args.world
        print(f"Img/sec per rank: {per:.1f}")
        print(f"Total img/sec on {args.world} rank(s): {mean:.1f} +-{1.96 * std:.1f}")
    else:
        rates = run_benchmark(args)
        mean, std = statistics.mean(rates), statistics.pstdev(rates)
        print(f"Img/sec: {mean:.1f} +-{1.96 * std:.1f}")


if __name__ == "__main__":
    main()
